"""Sharding the scheduling cycle over a TPU mesh.

The reference scales its hot loop with 16 worker goroutines over the node
axis (workqueue.ParallelizeUntil in PredicateNodes/PrioritizeNodes,
pkg/scheduler/util/scheduler_helper.go:124,160) plus adaptive node *sampling*
to bound latency (CalculateNumOfFeasibleNodesToFind, scheduler_helper.go:52-71).
The TPU design shards the node axis across devices instead — no sampling, the
full cluster is scored every cycle:

- NodeArrays tensors are sharded along axis 0 over a 1-D ``nodes`` mesh;
- task/job/queue state is replicated (it is small relative to nodes);
- per-task feasibility+scoring run device-local; the argmax and the capacity
  scatter are resolved by GSPMD-inserted collectives over ICI (an
  all-reduce-argmax per placement, the collective analog of SelectBestNode);
- with ``use_pallas`` requested the cycle composes both axes: each shard
  launches the shard-local pallas candidate kernel over its own node rows
  under shard_map, and the per-shard winners reduce through the same
  in-graph argmax combine (allocate_scan's sharded-pallas path). Decisions
  stay bit-identical either way.

Shapes from arrays.pack follow the graded bucket grid (arrays/schema.bucket):
powers of two up to 1024, multiples of 1024 above — so the node axis divides
any power-of-two mesh of up to 1024 devices, far beyond the mesh sizes this
control-plane workload runs on (the 16-goroutine analog, SURVEY section 2.5).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..arrays.schema import SnapshotArrays
from ..ops.allocate_scan import AllocateConfig, make_allocate_cycle

NODE_AXIS = "nodes"


def scheduler_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (NODE_AXIS,))


#: live-device-id tuple -> Mesh, so every kernel over the same device SET
#: shares one Mesh object (NamedShardings compare equal, jit caches stay
#: shared). Keying by the device tuple — not the shard count — is what
#: makes quarantine safe: after a device-set change a count-keyed cache
#: would keep handing out a Mesh whose array still references the dead
#: device. The health registry invalidates on every quarantine/regrow.
_MESH_CACHE: Dict[tuple, Mesh] = {}


def invalidate_mesh_cache() -> None:
    """Drop every cached Mesh — the hook the device-health registry fires
    when the healthy-device set changes (quarantine or probation regrow),
    so the next mesh_for_nodes rebuilds over the survivors."""
    _MESH_CACHE.clear()


def mesh_for_nodes(n_nodes: int, requested: Optional[int] = None) -> Mesh:
    """The production mesh for a snapshot with ``n_nodes`` packed node
    rows: the largest power-of-two device count <= ``requested`` (default:
    all local devices) that divides the node axis, built over the HEALTHY
    devices and clamped by the registry's shrink cap (parallel/health.py)
    — after a quarantine every consumer of this function (Scheduler
    session, sidecar, fleet bucket keys) transparently re-meshes at the
    next halved width over the survivors. The bucket grid
    (arrays/schema.bucket) keeps n_nodes a power of two up to 1024 and a
    multiple of 1024 above, so any pow2 mesh up to 1024 divides it; the
    clamp only bites on sub-bucket test snapshots."""
    from .health import HEALTH
    devices = HEALTH.healthy_devices()
    avail = len(devices)
    want = avail if requested is None else max(1, min(int(requested), avail))
    if HEALTH.width_cap is not None:
        want = max(1, min(want, HEALTH.width_cap))
    d = 1
    while d * 2 <= want and n_nodes % (d * 2) == 0:
        d *= 2
    chosen = tuple(devices[:d])
    key = tuple(dev.id for dev in chosen)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = _MESH_CACHE[key] = Mesh(np.array(chosen), (NODE_AXIS,))
    return mesh


def node_leaf_mask(tree) -> tuple:
    """bool per flattened leaf of a cycle argument tree — True exactly for
    the leaves of ``tree[0].nodes`` (the NodeArrays block of the leading
    SnapshotArrays). Computed STRUCTURALLY (a mask pytree of the same
    shape), so a new NodeArrays field can never silently classify as
    replicated — the same can't-drift guarantee node_sharding_specs gets
    from its jax.tree.map."""
    snap = tree[0]
    if not isinstance(snap, SnapshotArrays):
        raise TypeError("cycle tree must lead with SnapshotArrays, got "
                        f"{type(snap).__name__}")
    mask = list(jax.tree.map(lambda _: False, tuple(tree)))
    mask[0] = dataclasses.replace(
        mask[0], nodes=jax.tree.map(lambda _: True, snap.nodes))
    return tuple(jax.tree.leaves(tuple(mask)))


def node_sharding_specs(mesh: Mesh, snap: SnapshotArrays):
    """(in_shardings for snap, replicated spec) — node tensors split on the
    node axis, everything else replicated. The node block maps EVERY
    NodeArrays field to the row spec via jax.tree.map, so a new node
    field can't silently ship replicated."""
    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P(NODE_AXIS))
    snap_shardings = SnapshotArrays(
        nodes=jax.tree.map(lambda _: row, snap.nodes),
        tasks=jax.tree.map(lambda _: rep, snap.tasks),
        jobs=jax.tree.map(lambda _: rep, snap.jobs),
        queues=jax.tree.map(lambda _: rep, snap.queues),
        namespace_weight=rep,
        cluster_capacity=rep,
        template_rep=rep,
    )
    return snap_shardings, rep


def make_sharded_allocate(cfg: AllocateConfig, mesh: Mesh,
                          snap: SnapshotArrays):
    """jit the allocate cycle with the node axis sharded over ``mesh``.

    ``cfg.use_pallas`` is honored: passing the mesh into
    make_allocate_cycle selects the sharded-pallas path (shard-local
    candidate launches under shard_map, cross-shard argmax combine)
    instead of a full-axis pallas_call GSPMD could not partition.
    """
    snap_shardings, rep = node_sharding_specs(mesh, snap)
    extras_rep = None  # let GSPMD replicate extras by default
    fn = make_allocate_cycle(cfg, mesh=mesh)
    return jax.jit(fn, in_shardings=(snap_shardings, extras_rep),
                   out_shardings=rep)


def make_sharded_preempt(pcfg, mesh: Mesh, snap: SnapshotArrays):
    """jit the preempt/reclaim cycle with the node axis sharded over
    ``mesh`` (same layout as make_sharded_allocate: node tensors split,
    task/job/queue state and extras replicated; the per-round segment-sums
    and the candidate walk's argmaxes resolve through GSPMD collectives).
    """
    from ..ops.preempt import make_preempt_cycle
    snap_shardings, rep = node_sharding_specs(mesh, snap)
    fn = make_preempt_cycle(pcfg)
    return jax.jit(fn, in_shardings=(snap_shardings, None, None, None),
                   out_shardings=rep)


# --------------------------------------------------------------------------
# Production execution mode: sharded device-resident delta cycle (ISSUE 7)
# --------------------------------------------------------------------------

def make_sharded_delta(cfg: AllocateConfig, mesh: Mesh, tree,
                       entry: str = "fused_cycle_sharded"):
    """ShardedDeltaKernel for the allocate cycle over ``mesh``: node-axis
    residents, routed deltas, per-shard digests, donation through pjit.

    ``cfg.use_pallas`` is honored the same way
    :func:`make_sharded_allocate` does it — the mesh-aware cycle runs
    shard-local pallas candidate launches, never a full-axis
    pallas_call."""
    from ..ops.fused_io import ShardedDeltaKernel
    return ShardedDeltaKernel(make_allocate_cycle(cfg, mesh=mesh), tree,
                              mesh, node_leaf_mask(tree), entry=entry)


def sharded_delta_allocate_cached(cfg: AllocateConfig, tree, mesh,
                                  cache: Dict):
    """Shape+mesh-memoized :func:`make_sharded_delta` (the sharded analog
    of fused_io.delta_cycle_cached, same key construction)."""
    from ..ops.fused_io import sharded_delta_cycle_cached
    return sharded_delta_cycle_cached(make_allocate_cycle(cfg, mesh=mesh),
                                      tree, mesh, node_leaf_mask(tree),
                                      cache, key_extra=cfg)
