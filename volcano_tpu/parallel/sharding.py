"""Sharding the scheduling cycle over a TPU mesh.

The reference scales its hot loop with 16 worker goroutines over the node
axis (workqueue.ParallelizeUntil in PredicateNodes/PrioritizeNodes,
pkg/scheduler/util/scheduler_helper.go:124,160) plus adaptive node *sampling*
to bound latency (CalculateNumOfFeasibleNodesToFind, scheduler_helper.go:52-71).
The TPU design shards the node axis across devices instead — no sampling, the
full cluster is scored every cycle:

- NodeArrays tensors are sharded along axis 0 over a 1-D ``nodes`` mesh;
- task/job/queue state is replicated (it is small relative to nodes);
- per-task feasibility+scoring run device-local; the argmax and the capacity
  scatter are resolved by GSPMD-inserted collectives over ICI (an
  all-reduce-argmax per placement, the collective analog of SelectBestNode).

Shapes from arrays.pack follow the graded bucket grid (arrays/schema.bucket):
powers of two up to 1024, multiples of 1024 above — so the node axis divides
any power-of-two mesh of up to 1024 devices, far beyond the mesh sizes this
control-plane workload runs on (the 16-goroutine analog, SURVEY section 2.5).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..arrays.schema import NodeArrays, SnapshotArrays
from ..ops.allocate_scan import AllocateConfig, make_allocate_cycle

NODE_AXIS = "nodes"


def scheduler_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (NODE_AXIS,))


def node_sharding_specs(mesh: Mesh, snap: SnapshotArrays):
    """(in_shardings for snap, replicated spec) — node tensors split on the
    node axis, everything else replicated."""
    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P(NODE_AXIS))

    def node_spec(leaf_name: str):
        return row

    node_shardings = NodeArrays(
        idle=row, used=row, releasing=row, pipelined=row, allocatable=row,
        capability=row, labels=row, taint_kv=row, taint_key=row,
        taint_effect=row, pod_count=row, max_pods=row,
        gpu_memory=row, gpu_used=row, schedulable=row,
        valid=row)
    snap_shardings = SnapshotArrays(
        nodes=node_shardings,
        tasks=jax.tree.map(lambda _: rep, snap.tasks),
        jobs=jax.tree.map(lambda _: rep, snap.jobs),
        queues=jax.tree.map(lambda _: rep, snap.queues),
        namespace_weight=rep,
        cluster_capacity=rep,
        template_rep=rep,
    )
    return snap_shardings, rep


def make_sharded_allocate(cfg: AllocateConfig, mesh: Mesh,
                          snap: SnapshotArrays):
    """jit the allocate cycle with the node axis sharded over ``mesh``.

    Forces the pure-XLA scan path: GSPMD has no partitioning rule for the
    pallas custom call, so letting use_pallas auto-enable here would at best
    replicate the full node axis on every device (defeating the sharding)
    and at worst fail to compile.
    """
    import dataclasses
    cfg = dataclasses.replace(cfg, use_pallas=False)
    snap_shardings, rep = node_sharding_specs(mesh, snap)
    extras_rep = None  # let GSPMD replicate extras by default
    fn = make_allocate_cycle(cfg)
    return jax.jit(fn, in_shardings=(snap_shardings, extras_rep),
                   out_shardings=rep)


def make_sharded_preempt(pcfg, mesh: Mesh, snap: SnapshotArrays):
    """jit the preempt/reclaim cycle with the node axis sharded over
    ``mesh`` (same layout as make_sharded_allocate: node tensors split,
    task/job/queue state and extras replicated; the per-round segment-sums
    and the candidate walk's argmaxes resolve through GSPMD collectives).
    """
    from ..ops.preempt import make_preempt_cycle
    snap_shardings, rep = node_sharding_specs(mesh, snap)
    fn = make_preempt_cycle(pcfg)
    return jax.jit(fn, in_shardings=(snap_shardings, None, None, None),
                   out_shardings=rep)
