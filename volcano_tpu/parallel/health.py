"""Device-health registry: quarantine, mesh shrink caps, probation regrow.

The degradation ladder built in ISSUE 5/10/11 assumed the mesh under the
sharded cycle is immortal: ``backend_loss`` is transient, so one sync
retry (or the CPU oracle) always finds the same devices alive. On a real
pod slice the dominant hard fault is the opposite — a chip or host dies
and STAYS dead — and retrying the same mesh forever pins the runtime to
the oracle rung. This registry is the missing piece of state:

- **Strike classification.** Sharded dispatch failures that can name
  their devices (``ChaosError(device_ids=...)``, or any exception chain
  carrying a ``device_ids`` attribute) are recorded per device. N strikes
  inside a sliding cycle window (``VOLCANO_MESH_STRIKES`` /
  ``VOLCANO_MESH_WINDOW``, default 2-in-8) classify the device as
  *persistently* lost and quarantine it; a lone strike stays transient
  and ages out, so the existing sync-retry rung keeps absorbing
  ``backend_loss``-style blips exactly as before.
- **Width halving.** Quarantine halves the serving-width cap (8 -> 4 ->
  2), never recomputes it from the healthy count — with 7 of 8 devices
  healthy the next pow2 down is what keeps the node axis divisible, and
  repeated losses must keep descending instead of sticking at 4.
  :func:`..parallel.sharding.mesh_for_nodes` consults the registry, so
  every mesh consumer (Scheduler session, sidecar, fleet bucket keys)
  re-meshes over the survivors with no new plumbing.
- **Probation regrow.** After a quiet probation interval the cap doubles
  back toward the full mesh and quarantined devices are released *on
  probation*: one strike inside ``VOLCANO_MESH_FLAP_WINDOW`` of release
  re-quarantines immediately (no second strike needed) and escalates the
  probation interval through a stateful :class:`..runtime.backoff.Backoff`
  — flap damping, so a device that dies every time it is readmitted costs
  a geometrically rarer re-mesh, not a re-mesh per cooldown.

The registry holds NO device truth — cluster state lives in the
FakeCluster/API-server analog and residents re-fuse from source truth on
the rebuilt mesh (the ISSUE 10 recovery primitive), which is why shrink
and regrow are decision-neutral by construction.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..runtime.backoff import Backoff


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def failed_devices(exc: BaseException) -> Tuple[int, ...]:
    """Device ids named by ``exc`` or anything in its cause/context chain
    (the attribution contract: persistent device faults carry a
    ``device_ids`` tuple; transient faults don't and stay anonymous)."""
    seen = set()
    node: Optional[BaseException] = exc
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        ids = getattr(node, "device_ids", None)
        if ids:
            try:
                return tuple(int(i) for i in ids)
            except (TypeError, ValueError):
                return ()
        node = node.__cause__ or node.__context__
    return ()


class DeviceHealthRegistry:
    """Process-wide strike/quarantine/regrow state for the device mesh."""

    def __init__(self) -> None:
        self.configure()

    # -- lifecycle ---------------------------------------------------------

    def configure(self, strikes: Optional[int] = None,
                  window: Optional[int] = None,
                  probation: Optional[int] = None,
                  flap_window: Optional[int] = None) -> None:
        """(Re)arm the registry, clearing all health state. Explicit args
        win over the ``VOLCANO_MESH_*`` env knobs; the chaos probes call
        this between runs so storms can't leak quarantines."""
        self.strikes = strikes if strikes is not None else _env_int(
            "VOLCANO_MESH_STRIKES", 2)
        self.window = window if window is not None else _env_int(
            "VOLCANO_MESH_WINDOW", 8)
        self.probation = probation if probation is not None else _env_int(
            "VOLCANO_MESH_PROBATION", 3)
        self.flap_window = flap_window if flap_window is not None else \
            _env_int("VOLCANO_MESH_FLAP_WINDOW", 6)
        # stateful interval: base = one probation, doubling per flap,
        # capped — jitterless/seeded so shrink/regrow cycles stay
        # deterministic under the chaos probes
        self._backoff = Backoff(base=float(self.probation),
                                cap=float(self.probation) * 16.0,
                                factor=2.0, jitter=0.0, seed=0)
        self.reset()

    def reset(self) -> None:
        self.quarantined: Dict[int, dict] = {}
        self.width_cap: Optional[int] = None
        self.generation: int = 0
        self._strikes: Dict[int, List[int]] = {}
        self._probation: Dict[int, int] = {}   # dev id -> release cycle
        self._interval: int = self.probation
        self._next_regrow: Optional[int] = None
        self._backoff.reset()
        self._invalidate_meshes()

    # -- failure intake ----------------------------------------------------

    def note_failure(self, exc: BaseException, cycle: int,
                     serving_width: Optional[int] = None
                     ) -> Tuple[int, ...]:
        """Record a dispatch failure; returns the devices this call newly
        quarantined (empty when the failure stayed transient or carried
        no device attribution). ``serving_width`` is the mesh width the
        failure occurred on — the halving base for the shrink cap."""
        newly = []
        for dev in failed_devices(exc):
            if dev in self.quarantined:
                continue
            release = self._probation.get(dev)
            on_probation = (release is not None
                            and cycle - release <= self.flap_window)
            log = self._strikes.setdefault(dev, [])
            log.append(cycle)
            del log[:max(0, len(log) - 8)]
            recent = [c for c in log if cycle - c < self.window]
            if on_probation or len(recent) >= self.strikes:
                self._quarantine(dev, cycle, flap=on_probation,
                                 serving_width=serving_width)
                newly.append(dev)
        return tuple(newly)

    def _quarantine(self, dev: int, cycle: int, flap: bool,
                    serving_width: Optional[int]) -> None:
        self.quarantined[dev] = {
            "cycle": cycle,
            "reason": "flap" if flap else "strikes",
            "strikes": len(self._strikes.get(dev, ())),
        }
        self._strikes.pop(dev, None)
        self._probation.pop(dev, None)
        base = serving_width if serving_width else self.width_cap
        if base is not None and base > 1:
            self.width_cap = max(1, int(base) // 2)
        if not flap:
            self._backoff.reset()
        self._interval = max(1, int(round(self._backoff.next())))
        self._next_regrow = cycle + self._interval
        self.generation += 1
        self._invalidate_meshes()

    # -- regrow ------------------------------------------------------------

    def tick(self, cycle: int) -> Optional[dict]:
        """Advance the probation clock. Returns a regrow descriptor when
        this cycle lifts the cap a step (and releases quarantined devices
        on probation), else None. Call once per scheduler cycle."""
        for dev, release in list(self._probation.items()):
            if cycle - release > self.flap_window:
                del self._probation[dev]       # survived probation clean
        if not self.quarantined and self.width_cap is None:
            if not self._probation:
                self._backoff.reset()
                self._interval = self.probation
            self._next_regrow = None
            return None
        if self._next_regrow is None or cycle < self._next_regrow:
            return None
        released = sorted(self.quarantined)
        for dev in released:
            del self.quarantined[dev]
            self._probation[dev] = cycle
            self._strikes.pop(dev, None)
        total = self._device_count()
        if self.width_cap is not None:
            self.width_cap *= 2
            if total and self.width_cap >= total:
                self.width_cap = None
        self.generation += 1
        self._invalidate_meshes()
        done = self.width_cap is None and not self.quarantined
        self._next_regrow = None if done else cycle + self._interval
        return {"width_cap": self.width_cap, "released": released,
                "interval": self._interval, "cycle": cycle}

    # -- mesh selection inputs --------------------------------------------

    def healthy_devices(self) -> list:
        import jax
        return [d for d in jax.devices() if d.id not in self.quarantined]

    def _device_count(self) -> int:
        try:
            import jax
            return len(jax.devices())
        except Exception:  # pragma: no cover - jax always importable here
            return 0

    def _invalidate_meshes(self) -> None:
        from .sharding import invalidate_mesh_cache
        invalidate_mesh_cache()

    # -- introspection / persistence --------------------------------------

    @property
    def probation_interval(self) -> int:
        return self._interval

    def snapshot(self) -> dict:
        """Checkpointable view (plain ints/dicts only)."""
        return {
            "quarantined": {int(k): dict(v)
                            for k, v in self.quarantined.items()},
            "width_cap": self.width_cap,
            "generation": self.generation,
            "strikes": {int(k): list(v) for k, v in self._strikes.items()},
            "probation": dict(self._probation),
            "interval": self._interval,
            "next_regrow": self._next_regrow,
            "backoff_attempt": self._backoff._attempt,
        }

    def restore(self, state: Optional[dict]) -> None:
        if not state:
            return
        self.quarantined = {int(k): dict(v) for k, v in
                            (state.get("quarantined") or {}).items()}
        self.width_cap = state.get("width_cap")
        self.generation = int(state.get("generation", 0))
        self._strikes = {int(k): list(v) for k, v in
                         (state.get("strikes") or {}).items()}
        self._probation = {int(k): int(v) for k, v in
                           (state.get("probation") or {}).items()}
        self._interval = int(state.get("interval", self.probation))
        self._next_regrow = state.get("next_regrow")
        self._backoff.reset()
        self._backoff._attempt = int(state.get("backoff_attempt", 0))
        self._invalidate_meshes()


#: the process-wide registry every mesh consumer consults
HEALTH = DeviceHealthRegistry()
