"""Plugin model: policy plugins as kernel contributors.

Re-design of the reference's callback-bag plugins (pkg/scheduler/framework/
interface.go:35-60, session_plugins.go:26-127 with its 20 Add*Fn extension
points): instead of registering Go closures dispatched per task×node, a
plugin contributes
- score weights folded into the compiled allocate pass,
- fairness arrays (deserved shares, job/namespace shares),
- admission gates for enqueue,
- victim preferences/vetoes for preempt/reclaim,
- and host-side session-close writebacks (conditions, metrics).

The Session queries these contributions once per cycle and bakes them into
the jitted kernels (SURVEY.md section 7: "plugins stop being callback bags
and become kernel contributors").
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional

import numpy as np

from ..framework.conf import PluginOption

if TYPE_CHECKING:  # pragma: no cover
    from ..framework.session import Session


class Plugin:
    """Base plugin. Subclasses override the contribution hooks they serve.

    Reference seam: framework.Plugin interface (interface.go:35-43) with
    OnSessionOpen/OnSessionClose.
    """

    name: str = ""

    def __init__(self, option: Optional[PluginOption] = None):
        self.option = option or PluginOption(name=self.name)

    # lifecycle --------------------------------------------------------------
    def on_session_open(self, ssn: "Session") -> None:
        pass

    def on_session_close(self, ssn: "Session") -> None:
        pass

    # compiled-pass contributions -------------------------------------------
    def score_weights(self, ssn: "Session") -> Dict[str, float]:
        """Additive weights merged into AllocateConfig (node-order terms)."""
        return {}

    def queue_deserved(self, ssn: "Session") -> Optional[np.ndarray]:
        """f32[Q, R] deserved share, or None if this plugin doesn't gate
        queue capacity (proportion's water-filling)."""
        return None

    def job_order_share(self, ssn: "Session") -> Optional[np.ndarray]:
        """f32[J] fairness key for job ordering (drf)."""
        return None

    def namespace_share(self, ssn: "Session") -> Optional[np.ndarray]:
        """f32[S] namespace ordering key (drf namespace fairness)."""
        return None

    def enqueue_gates(self, ssn: "Session") -> Dict[str, object]:
        """Contributions to EnqueueConfig (proportion/overcommit/sla)."""
        return {}

    def sla_waiting(self, ssn: "Session") -> Optional[np.ndarray]:
        """bool[J] jobs past their SLA waiting deadline."""
        return None

    # preempt/reclaim contributions (bool masks over the task axis) ----------
    def victim_veto(self, ssn: "Session") -> Optional[np.ndarray]:
        """bool[T] tasks this plugin forbids evicting (conformance, gang)."""
        return None

    def arg(self, key: str, default=None):
        return self.option.get_argument(key, default)

    def arg_float(self, key: str, default: float) -> float:
        v = self.arg(key)
        return float(v) if v is not None else default

    def arg_bool(self, key: str, default: bool) -> bool:
        v = self.arg(key)
        if v is None:
            return default
        return str(v).lower() in ("1", "true", "yes", "on")
