"""Reservation plugin: elect a starving target job and lock nodes for it.

Reference: pkg/scheduler/plugins/reservation/reservation.go:28-141 with the
elect/reserve actions (pkg/scheduler/actions/{elect,reserve}) and the global
Reservation singleton (pkg/scheduler/util/scheduler_helper.go:44-48,257-269):
the highest-priority, longest-waiting pending job becomes the target; while
it stays unready, the scheduler locks the emptiest unlocked node each cycle
so the target eventually fits.
"""

from __future__ import annotations

from typing import Optional, Set

import numpy as np

from .base import Plugin


class ReservationState:
    """Cross-cycle reservation state (the util.Reservation singleton)."""

    def __init__(self):
        self.target_job_uid: Optional[str] = None
        self.locked_nodes: Set[str] = set()

    def reset(self):
        self.target_job_uid = None
        self.locked_nodes.clear()


class ReservationPlugin(Plugin):
    name = "reservation"

    def __init__(self, option=None, state: Optional[ReservationState] = None):
        super().__init__(option)
        self.state = state or ReservationState()

    def elect_target(self, ssn) -> Optional[str]:
        """TargetJobFn: highest-priority then longest-waiting pending job
        (reservation.go:39-54)."""
        best_uid, best_key = None, None
        for uid, job in ssn.cluster.jobs.items():
            if job.pending_task_num() == 0 or job.is_ready():
                continue
            key = (-job.priority, job.creation_timestamp)
            if best_key is None or key < best_key:
                best_key, best_uid = key, uid
        return best_uid

    def reserve_node(self, ssn) -> Optional[str]:
        """ReservedNodesFn: lock the unlocked node with the most idle
        resources (reservation.go:56-63)."""
        best_name, best_idle = None, -1.0
        for name, node in ssn.cluster.nodes.items():
            if name in self.state.locked_nodes:
                continue
            idle = node.idle.milli_cpu
            if idle > best_idle:
                best_idle, best_name = idle, name
        return best_name

    def node_locked_mask(self, ssn) -> np.ndarray:
        N = np.asarray(ssn.snap.nodes.pod_count).shape[0]
        locked = np.zeros(N, bool)
        for name in self.state.locked_nodes:
            ni = ssn.maps.node_index.get(name)
            if ni is not None:
                locked[ni] = True
        return locked

    def target_job_index(self, ssn) -> int:
        if self.state.target_job_uid is None:
            return -1
        return ssn.maps.job_index.get(self.state.target_job_uid, -1)
