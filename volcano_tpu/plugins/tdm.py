"""TDM (time-division multiplexing) plugin: revocable-zone scheduling windows.

Reference: pkg/scheduler/plugins/tdm/tdm.go:58-372 — nodes annotated with a
revocable zone only admit preemptable tasks while the zone's configured
daily window (``tdm.revocable-zone.<zone>: "hh:mm-hh:mm"``) is active; a
score bonus steers preemptable tasks there during the window; outside the
window, preemptable tasks on revocable nodes become victims (evicted in
max-step batches by the victimsFn, tdm.go:298).
"""

from __future__ import annotations

import datetime
from typing import Dict, Tuple

import numpy as np

from .base import Plugin

REVOCABLE_ZONE_LABEL = "volcano.sh/revocable-zone"


def _parse_window(spec: str) -> Tuple[int, int]:
    start, end = spec.strip().split("-")
    h1, m1 = (int(x) for x in start.split(":"))
    h2, m2 = (int(x) for x in end.split(":"))
    return h1 * 60 + m1, h2 * 60 + m2


class TDMPlugin(Plugin):
    name = "tdm"

    def _zones(self) -> Dict[str, Tuple[int, int]]:
        zones = {}
        for key, val in self.option.arguments.items():
            if key.startswith("tdm.revocable-zone."):
                zones[key[len("tdm.revocable-zone."):]] = _parse_window(str(val))
        return zones

    def _zone_active(self, zone: str, now: float) -> bool:
        window = self._zones().get(zone)
        if window is None:
            return False
        t = datetime.datetime.fromtimestamp(now)
        minute = t.hour * 60 + t.minute
        lo, hi = window
        return lo <= minute <= hi if lo <= hi else (minute >= lo or minute <= hi)

    def node_zone(self, ssn, name: str) -> str:
        node = ssn.cluster.nodes.get(name)
        return (node.labels.get(REVOCABLE_ZONE_LABEL, "") if node else "")

    def revocable_node_mask(self, ssn) -> np.ndarray:
        """bool[N]: node carries a revocable zone (window-independent) —
        the tdm victim rule's node filter (tdm.go:210-214)."""
        N = np.asarray(ssn.snap.nodes.pod_count).shape[0]
        mask = np.zeros(N, bool)
        for name, ni in ssn.maps.node_index.items():
            if self.node_zone(ssn, name):
                mask[ni] = True
        return mask

    def block_nonpreempt(self, ssn) -> np.ndarray:
        """bool[N]: revocable nodes (active window) admit only preemptable
        tasks; outside the window they admit nothing new (tdm.go:295)."""
        N = np.asarray(ssn.snap.nodes.pod_count).shape[0]
        block = np.zeros(N, bool)
        for name, ni in ssn.maps.node_index.items():
            if self.node_zone(ssn, name):
                block[ni] = True
        return block

    def victim_tasks(self, ssn) -> np.ndarray:
        """bool[T]: preemptable tasks sitting on revocable nodes whose window
        is closed — the periodic eviction sweep (tdm.go:298-340)."""
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        victims = np.zeros(T, bool)
        preemptable = np.asarray(ssn.snap.tasks.preemptable)
        for uid, ti in ssn.maps.task_index.items():
            task = None
            for job in ssn.cluster.jobs.values():
                task = job.tasks.get(uid)
                if task is not None:
                    break
            if task is None or not task.node_name:
                continue
            zone = self.node_zone(ssn, task.node_name)
            if zone and preemptable[ti] and not self._zone_active(zone, ssn.now):
                victims[ti] = True
        return victims
