"""TDM (time-division multiplexing) plugin: revocable-zone scheduling windows.

Reference: pkg/scheduler/plugins/tdm/tdm.go:58-372 — nodes labeled with a
revocable zone follow a configured daily window
(``tdm.revocable-zone.<zone>: "hh:mm-hh:mm"``):

- predicate (tdm.go:149-167): during the window a revocable node admits
  ONLY tasks that may use revocable zones (``volcano.sh/revocable-zone``
  "*", job_info.go:88-92); outside the window it admits nothing new,
- node order (tdm.go:170-191): MaxNodeScore bonus steering revocable tasks
  onto active-window revocable nodes,
- preemptable (tdm.go:193-229): kernel victim rule — preemptable Running
  tasks on NON-revocable nodes, with preemptable preemptors abstaining,
- victimsFn (tdm.go:232-260): periodic sweep evicting preemptable tasks
  from revocable nodes whose window closed, batched per job by the
  disruption budget (maxVictims, tdm.go:312-340), at most once per
  ``tdm.evict-period`` (default 1m),
- job order / pipelined / starving (tdm.go:261-298): non-preemptable jobs
  first; preemptable jobs never preempt.
"""

from __future__ import annotations

import datetime
from typing import Dict, Tuple

import numpy as np

from .base import Plugin

REVOCABLE_ZONE_LABEL = "volcano.sh/revocable-zone"

#: victimsFn fallback cap when no budget annotation is set (tdm.go:42)
DEFAULT_POD_EVICT_NUM = 1


def _parse_window(spec: str) -> Tuple[int, int]:
    start, end = spec.strip().split("-")
    h1, m1 = (int(x) for x in start.split(":"))
    h2, m2 = (int(x) for x in end.split(":"))
    return h1 * 60 + m1, h2 * 60 + m2


def _parse_duration(spec: str) -> float:
    """'1m' / '30s' / '2h' -> seconds (time.ParseDuration subset)."""
    spec = str(spec).strip()
    units = {"s": 1.0, "m": 60.0, "h": 3600.0}
    if spec and spec[-1] in units:
        return float(spec[:-1]) * units[spec[-1]]
    return float(spec)


def _parse_int_or_percent(value: str, total: int) -> int:
    """intstr.GetValueFromIntOrPercent with round-up (tdm.go:343-358)."""
    s = str(value).strip()
    if s.endswith("%"):
        pct = float(s[:-1])
        return int(-(-pct * total // 100))      # ceil
    try:
        return int(s)
    except ValueError:
        return 0


class TDMPlugin(Plugin):
    name = "tdm"

    def __init__(self, option):
        super().__init__(option)
        self.evict_period = _parse_duration(
            option.arguments.get("tdm.evict-period", "1m"))
        self._last_evict_at = float("-inf")   # persists across cycles when
        #                                       the plugin instance does

    def _zones(self) -> Dict[str, Tuple[int, int]]:
        zones = {}
        for key, val in self.option.arguments.items():
            if key.startswith("tdm.revocable-zone."):
                zones[key[len("tdm.revocable-zone."):]] = _parse_window(str(val))
        return zones

    def _zone_active(self, zone: str, now: float) -> bool:
        window = self._zones().get(zone)
        if window is None:
            return False
        t = datetime.datetime.fromtimestamp(now)
        minute = t.hour * 60 + t.minute
        lo, hi = window
        return lo <= minute <= hi if lo <= hi else (minute >= lo or minute <= hi)

    def node_zone(self, ssn, name: str) -> str:
        node = ssn.cluster.nodes.get(name)
        return (node.labels.get(REVOCABLE_ZONE_LABEL, "") if node else "")

    def _node_masks(self, ssn):
        """(revocable bool[N], active bool[N]) per packed node."""
        N = np.asarray(ssn.snap.nodes.pod_count).shape[0]
        revocable = np.zeros(N, bool)
        active = np.zeros(N, bool)
        for name, ni in ssn.maps.node_index.items():
            zone = self.node_zone(ssn, name)
            if zone:
                revocable[ni] = True
                active[ni] = self._zone_active(zone, ssn.now)
        return revocable, active

    def revocable_node_mask(self, ssn) -> np.ndarray:
        """bool[N]: node carries a revocable zone (window-independent) —
        the tdm victim rule's node filter (tdm.go:210-214)."""
        return self._node_masks(ssn)[0]

    def block_nonrevocable(self, ssn) -> np.ndarray:
        """bool[N]: ACTIVE-window revocable nodes — admit only tasks with a
        revocable zone (tdm.go:158-165)."""
        revocable, active = self._node_masks(ssn)
        return revocable & active

    def block_all_mask(self, ssn) -> np.ndarray:
        """bool[N]: INACTIVE-window revocable nodes — admit nothing new
        (tdm.go:152-156 predicate error for every task)."""
        revocable, active = self._node_masks(ssn)
        return revocable & ~active

    def task_revocable_mask(self, ssn) -> np.ndarray:
        """bool[T]: tasks allowed onto revocable nodes (revocable_zone
        '*', job_info.go:88-92)."""
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        mask = np.zeros(T, bool)
        for job in ssn.cluster.jobs.values():
            for uid, task in job.tasks.items():
                ti = ssn.maps.task_index.get(uid)
                if ti is not None and task.revocable_zone:
                    mask[ti] = True
        return mask

    def tdm_bonus_mask(self, ssn) -> np.ndarray:
        """f32[N]: MaxNodeScore on active-window revocable nodes — the
        nodeOrderFn bonus for revocable tasks (tdm.go:170-191)."""
        revocable, active = self._node_masks(ssn)
        return np.where(revocable & active, 100.0, 0.0).astype(np.float32)

    def _max_evict(self, job) -> int:
        """Per-job victim cap from the disruption budget
        (getMaxPodEvictNum, tdm.go:312-340)."""
        from ..api import TaskStatus
        tasks = list(job.tasks.values())
        n = len(tasks)
        running = sum(1 for t in tasks if t.status == TaskStatus.RUNNING)
        if job.budget_max_unavailable:
            max_unavail = _parse_int_or_percent(job.budget_max_unavailable, n)
            final = sum(1 for t in tasks
                        if t.status in (TaskStatus.SUCCEEDED,
                                        TaskStatus.FAILED))
            real_unavail = n - final - running
            if real_unavail >= max_unavail:
                return 0
            return max_unavail - real_unavail
        if job.budget_min_available:
            min_avail = _parse_int_or_percent(job.budget_min_available, n)
            return max(running - min_avail, 0)
        return DEFAULT_POD_EVICT_NUM

    def job_victim_budget(self, ssn) -> np.ndarray:
        """i32[J]: per-job eviction budget for the preempt path — the
        maxVictims cap the reference applies INSIDE its tdm Preemptable fn
        (tdm.go:219-229 -> maxVictims -> getMaxPodEvictNum,
        tdm.go:304-340), consumed in-kernel so placement-path evictions
        respect the disruption budget too."""
        J = np.asarray(ssn.snap.jobs.valid).shape[0]
        budget = np.full(J, 2 ** 31 - 1, np.int32)
        for uid, ji in ssn.maps.job_index.items():
            job = ssn.cluster.jobs.get(uid)
            if job is not None:
                budget[ji] = self._max_evict(job)
        return budget

    def victim_tasks(self, ssn) -> np.ndarray:
        """bool[T]: preemptable tasks on closed-window revocable nodes —
        the periodic sweep (tdm.go:232-260), per-job maxVictims batching
        (tdm.go:312-318), rate-limited to one run per evict period
        (tdm.go:233-236)."""
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        victims = np.zeros(T, bool)
        if ssn.now - self._last_evict_at < self.evict_period:
            return victims
        self._last_evict_at = ssn.now
        per_job: Dict[str, list] = {}
        for job in ssn.cluster.jobs.values():
            for uid, task in job.tasks.items():
                if not task.preemptable or not task.node_name:
                    continue
                zone = self.node_zone(ssn, task.node_name)
                if zone and not self._zone_active(zone, ssn.now):
                    per_job.setdefault(job.uid, []).append(uid)
        for juid, uids in per_job.items():
            cap = self._max_evict(ssn.cluster.jobs[juid])
            for uid in sorted(uids)[:cap]:
                ti = ssn.maps.task_index.get(uid)
                if ti is not None:
                    victims[ti] = True
        return victims
