"""DRF plugin: dominant-resource fairness (job order, namespace fairness,
hierarchical queues).

Reference: pkg/scheduler/plugins/drf/drf.go:35-797 — per-job dominant share
(drfAttr, drf.go:104-131), weighted namespace fairness (drf.go:474-507), and
the fork's hierarchical DRF over queue hierarchy annotations
(drf.go:42-87, 230-360). Shares are computed by the kernels in
ops/fairshare.py; this class wires snapshot arrays to them.
"""

from __future__ import annotations

import numpy as np

from .base import Plugin


class DRFPlugin(Plugin):
    name = "drf"

    def job_order_share(self, ssn) -> np.ndarray:
        jobs = ssn.snap.jobs
        total = np.maximum(np.asarray(ssn.snap.cluster_capacity), 1e-9)
        alloc = np.asarray(jobs.allocated)
        frac = np.where(total > 0, alloc / total, 0.0)
        share = frac.max(axis=-1)
        return np.where(np.asarray(jobs.valid), share, np.inf).astype(np.float32)

    def namespace_share(self, ssn) -> np.ndarray:
        if not self.option.enabled_namespace_order:
            return None
        jobs = ssn.snap.jobs
        S = np.asarray(ssn.snap.namespace_weight).shape[0]
        total = np.maximum(np.asarray(ssn.snap.cluster_capacity), 1e-9)
        ns_alloc = np.zeros((S, total.shape[0]), np.float32)
        alloc = np.asarray(jobs.allocated)
        ns_idx = np.asarray(jobs.namespace)
        valid = np.asarray(jobs.valid)
        np.add.at(ns_alloc, ns_idx[valid], alloc[valid])
        share = (ns_alloc / total).max(axis=-1)
        return (share / np.maximum(np.asarray(ssn.snap.namespace_weight), 1.0)
                ).astype(np.float32)

    # hdrf: the hierarchical queue ordering is computed in-kernel from
    # AllocateExtras.hierarchy (arrays/hierarchy.py) when the option's
    # enabled_hierarchy sets AllocateConfig.enable_hdrf — see
    # ops/fairshare.hdrf_level_keys for the exact drf.go:182-218 walk.
