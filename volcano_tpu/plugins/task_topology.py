"""Task-topology plugin: role affinity/anti-affinity within a job.

Reference: pkg/scheduler/plugins/task-topology/{topology,manager,bucket}.go
(964 LoC) — tasks of affine roles are grouped into buckets steered onto the
same node; anti-affine roles are pushed apart. The bucket bookkeeping is
host-side (like the reference's JobManager); the placement steer is the
``task_pref_node`` score bonus in the allocate kernel.

Annotation format (topology.go): job annotation ``volcano.sh/task-topology``
with arguments ``task-topology.affinity: "role1,role2;..."`` and
``task-topology.anti-affinity`` pairs.
"""

from __future__ import annotations

from typing import Dict, List, Set

import numpy as np

from .base import Plugin

AFFINITY_ARG = "task-topology.affinity"
ANTI_AFFINITY_ARG = "task-topology.anti-affinity"


def _parse_pairs(spec: str) -> List[Set[str]]:
    groups = []
    for part in str(spec).split(";"):
        roles = {r.strip() for r in part.split(",") if r.strip()}
        if roles:
            groups.append(roles)
    return groups


class TaskTopologyPlugin(Plugin):
    name = "task-topology"

    def task_pref_node(self, ssn) -> np.ndarray:
        """i32[T]: preferred node per pending task — the node already hosting
        a bucket-mate (affine running/bound task of the same job)."""
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        pref = np.full(T, -1, np.int32)
        affinity = _parse_pairs(self.arg(AFFINITY_ARG, ""))
        if not affinity:
            return pref
        for uid, job in ssn.cluster.jobs.items():
            # node of the first placed task per role
            role_node: Dict[str, str] = {}
            for task in job.tasks.values():
                if task.node_name and task.task_role:
                    role_node.setdefault(task.task_role, task.node_name)
            if not role_node:
                continue
            for task in job.tasks.values():
                ti = ssn.maps.task_index.get(task.uid)
                if ti is None or task.node_name:
                    continue
                for group in affinity:
                    if task.task_role in group:
                        for other in group:
                            node = role_node.get(other)
                            if node and node in ssn.maps.node_index:
                                pref[ti] = ssn.maps.node_index[node]
                                break
        return pref
