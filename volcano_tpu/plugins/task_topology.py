"""Task-topology plugin: role affinity/anti-affinity within a job.

Reference: pkg/scheduler/plugins/task-topology/{topology,manager,bucket}.go —
a per-job JobManager groups tasks of affine roles into BUCKETS (manager.go
buildBucket greedy assignment maximizing checkTaskSetAffinity, balancing
bucket resource scores, seeding buckets per already-placed node), orders the
job's pending tasks so bucket-mates schedule consecutively (topology.go
TaskOrderFn: in-bucket before out-of-bucket, larger bucket first, older
bucket first, then the user task-order / affinity-priority comparator), and
steers each bucket onto the node already holding most of it.

Topology comes from the PodGroup annotations
(``volcano.sh/task-topology-affinity``, ``-anti-affinity``, ``-task-order``;
util.go:36-40, "a,b;c,d" groups) or, legacy for this framework, from plugin
arguments applied to every job. Task roles come from
``TaskInfo.task_role``, falling back to the pod-name segment the reference
parses (getTaskName, util.go:69-71).

The bucket bookkeeping is host-side like the reference's JobManager; the
placement steer reaches the kernel as the ``task_pref_node`` bonus,
pointing each bucket task at the node holding the most bucket-mates. The
reference's per-(task,node) dynamic bucket score (topology.go
calcBucketScore) updating within the cycle is approximated by this static
per-cycle steer — documented divergence.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Set

import numpy as np

from ..api.resource import Resource
from .base import Plugin

AFFINITY_ARG = "task-topology.affinity"
ANTI_AFFINITY_ARG = "task-topology.anti-affinity"
TASK_ORDER_ARG = "task-topology.task-order"

AFFINITY_ANNOTATION = "volcano.sh/task-topology-affinity"
ANTI_AFFINITY_ANNOTATION = "volcano.sh/task-topology-anti-affinity"
TASK_ORDER_ANNOTATION = "volcano.sh/task-topology-task-order"

OUT_OF_BUCKET = -1

#: topology kind -> priority (manager.go:40-45; larger = higher)
_PRI_SELF_ANTI, _PRI_INTER_AFF, _PRI_SELF_AFF, _PRI_INTER_ANTI = 4, 3, 2, 1


def _parse_groups(spec: str) -> List[List[str]]:
    groups = []
    for part in str(spec).split(";"):
        roles = [r.strip() for r in part.split(",") if r.strip()]
        if roles:
            groups.append(roles)
    return groups


def _task_role(task) -> str:
    """TaskInfo -> role name (getTaskName, util.go:69-71: the reference
    parses the second-to-last dash segment of the pod name)."""
    if task.task_role:
        return task.task_role
    parts = task.name.split("-")
    return parts[-2] if len(parts) >= 2 else ""


def _req_score(req: Resource) -> float:
    """1 milli-cpu == 1 Mi == 1 scalar unit (bucket.go CalcResReq)."""
    score = 0.0
    for name, v in req.quantities.items():
        if name == "memory":
            score += v / (1024 * 1024)
        else:
            score += v
    return score


class Bucket:
    """bucket.go:24-109."""

    def __init__(self, index: int):
        self.index = index
        self.tasks: Dict[str, object] = {}       # pending, by uid
        self.task_name_set: Dict[str, int] = {}
        self.req_score = 0.0
        self.request = Resource()
        self.bound_task = 0
        self.node: Dict[str, int] = {}

    def add_task(self, role: str, task) -> None:
        self.task_name_set[role] = self.task_name_set.get(role, 0) + 1
        if task.node_name:
            self.node[task.node_name] = self.node.get(task.node_name, 0) + 1
            self.bound_task += 1
            return
        self.tasks[task.uid] = task
        self.req_score += _req_score(task.resreq)
        self.request.add(task.resreq)

    @property
    def size(self) -> int:
        return len(self.tasks) + self.bound_task


class JobManager:
    """manager.go:48-345 — per-job topology bookkeeping and buckets."""

    def __init__(self, job_uid: str):
        self.job_uid = job_uid
        self.buckets: List[Bucket] = []
        self.pod_in_bucket: Dict[str, int] = {}      # task uid -> bucket idx
        self.task_affinity_priority: Dict[str, int] = {}
        self.task_exist_order: Dict[str, int] = {}
        self.inter_affinity: Dict[str, Set[str]] = {}
        self.self_affinity: Set[str] = set()
        self.inter_anti_affinity: Dict[str, Set[str]] = {}
        self.self_anti_affinity: Set[str] = set()
        self.bucket_max_size = 0

    # ---------------------------------------------------------- topology
    def _mark(self, role: str, priority: int) -> None:
        if priority > self.task_affinity_priority.get(role, 0):
            self.task_affinity_priority[role] = priority

    def apply_topology(self, affinity: List[List[str]],
                       anti_affinity: List[List[str]],
                       task_order: List[str]) -> None:
        """ApplyTaskTopology (manager.go:111-148): group lists -> pairwise
        matrices; single-role groups mean self-(anti-)affinity."""
        for group in affinity:
            if len(group) == 1:
                self.self_affinity.add(group[0])
                self._mark(group[0], _PRI_SELF_AFF)
                continue
            for i, src in enumerate(group):
                for dst in group[:i]:
                    self.inter_affinity.setdefault(src, set()).add(dst)
                    self.inter_affinity.setdefault(dst, set()).add(src)
                self._mark(src, _PRI_INTER_AFF)
        for group in anti_affinity:
            if len(group) == 1:
                self.self_anti_affinity.add(group[0])
                self._mark(group[0], _PRI_SELF_ANTI)
                continue
            for i, src in enumerate(group):
                for dst in group[:i]:
                    self.inter_anti_affinity.setdefault(src, set()).add(dst)
                    self.inter_anti_affinity.setdefault(dst, set()).add(src)
                self._mark(src, _PRI_INTER_ANTI)
        for i, role in enumerate(task_order):
            self.task_exist_order[role] = len(task_order) - i

    # ------------------------------------------------------------ ordering
    def task_affinity_order(self, l_role: str, r_role: str) -> int:
        """manager.go:168-199: user-defined order first, then topology
        priority; 1 = l first."""
        if l_role == r_role:
            return 0
        lo = self.task_exist_order.get(l_role, 0)
        ro = self.task_exist_order.get(r_role, 0)
        if lo != ro:
            return 1 if lo > ro else -1
        lp = self.task_affinity_priority.get(l_role, 0)
        rp = self.task_affinity_priority.get(r_role, 0)
        if lp != rp:
            return 1 if lp > rp else -1
        return 0

    def check_task_set_affinity(self, role: str, name_set: Dict[str, int],
                                only_anti: bool) -> int:
        """manager.go:231-264: net affinity of ``role`` toward a bucket's
        role multiset."""
        score = 0
        if not role:
            return 0
        for other, count in name_set.items():
            same = other == role
            if not only_anti:
                aff = (role in self.self_affinity if same
                       else other in self.inter_affinity.get(role, ()))
                if aff:
                    score += count
            anti = (role in self.self_anti_affinity if same
                    else other in self.inter_anti_affinity.get(role, ()))
            if anti:
                score -= count
        return score

    # ------------------------------------------------------------- buckets
    def construct_buckets(self, tasks: List) -> None:
        """ConstructBucket (manager.go:306-318): order tasks (placed first,
        then the affinity comparator descending), then greedily assign each
        to the bucket with the best net affinity, balancing resource scores
        on ties; negative affinity opens a fresh bucket (buildBucket,
        manager.go:266-304)."""
        managed = []
        for task in tasks:
            role = _task_role(task)
            if not role or role not in self.task_affinity_priority:
                self.pod_in_bucket[task.uid] = OUT_OF_BUCKET
                continue
            managed.append((role, task))

        def cmp(a, b):
            ha, hb = bool(a[1].node_name), bool(b[1].node_name)
            if ha != hb:
                return -1 if ha else 1           # placed tasks first
            return -self.task_affinity_order(a[0], b[0])

        managed.sort(key=functools.cmp_to_key(cmp))

        node_bucket: Dict[str, Bucket] = {}
        for role, task in managed:
            selected: Optional[Bucket] = None
            max_aff = -(1 << 31)
            if task.node_name:
                max_aff = 0
                selected = node_bucket.get(task.node_name)
            else:
                for bucket in self.buckets:
                    aff = self.check_task_set_affinity(
                        role, bucket.task_name_set, only_anti=False)
                    if aff > max_aff:
                        max_aff, selected = aff, bucket
                    elif (aff == max_aff and selected is not None
                          and bucket.req_score < selected.req_score):
                        selected = bucket
            if max_aff < 0 or selected is None:
                selected = Bucket(len(self.buckets))
                self.buckets.append(selected)
                if task.node_name:
                    node_bucket[task.node_name] = selected
            self.pod_in_bucket[task.uid] = selected.index
            selected.add_task(role, task)
            self.bucket_max_size = max(self.bucket_max_size, selected.size)

    def get_bucket(self, uid: str) -> Optional[Bucket]:
        idx = self.pod_in_bucket.get(uid, OUT_OF_BUCKET)
        return None if idx == OUT_OF_BUCKET else self.buckets[idx]


class TaskTopologyPlugin(Plugin):
    name = "task-topology"

    def _job_topology(self, job):
        """(affinity, anti, order) groups from the job's annotations, or
        the plugin arguments as the every-job fallback."""
        ann = getattr(job, "annotations", {}) or {}
        aff = ann.get(AFFINITY_ANNOTATION, self.arg(AFFINITY_ARG, ""))
        anti = ann.get(ANTI_AFFINITY_ANNOTATION,
                       self.arg(ANTI_AFFINITY_ARG, ""))
        order = ann.get(TASK_ORDER_ANNOTATION, self.arg(TASK_ORDER_ARG, ""))
        return (_parse_groups(aff or ""), _parse_groups(anti or ""),
                [r.strip() for r in str(order or "").split(",") if r.strip()])

    def managers(self, ssn) -> Dict[str, JobManager]:
        """Per-session JobManagers (initBucket, topology.go:215-240)."""
        cached = getattr(ssn, "_topology_managers", None)
        if cached is not None:
            return cached
        managers: Dict[str, JobManager] = {}
        for uid, job in ssn.cluster.jobs.items():
            aff, anti, order = self._job_topology(job)
            if not (aff or anti or order):
                continue
            jm = JobManager(uid)
            jm.apply_topology(aff, anti, order)
            jm.construct_buckets(list(job.tasks.values()))
            managers[uid] = jm
        ssn._topology_managers = managers
        return managers

    def on_session_open(self, ssn) -> None:
        """Reorder each managed job's pending task table to the
        TaskOrderFn semantics (topology.go:61-131): in-bucket before
        out-of-bucket, larger bucket first, older bucket first, then the
        user-order / priority comparator — ahead of the packed (priority,
        insertion) fallback order."""
        managers = self.managers(ssn)
        if not managers:
            return
        table = np.asarray(ssn.snap.jobs.task_table).copy()
        uids = ssn.maps.task_uids
        changed = False
        for juid, jm in managers.items():
            ji = ssn.maps.job_index.get(juid)
            if ji is None:
                continue
            row = table[ji]
            real = row[row >= 0]
            if not len(real):
                continue

            def key(ti):
                uid = uids[int(ti)]
                bucket = jm.get_bucket(uid)
                if bucket is None:
                    return (1, 0, 0, 0, 0)
                _job, task = ssn._task_lookup.get(uid, (None, None))
                role = _task_role(task) if task is not None else ""
                return (0, -bucket.size, bucket.index,
                        -jm.task_exist_order.get(role, 0),
                        -jm.task_affinity_priority.get(role, 0))

            order = sorted(range(len(real)),
                           key=lambda i: (key(real[i]), i))
            table[ji, :len(real)] = real[order]
            changed = True
        if changed:
            import dataclasses
            ssn.snap = dataclasses.replace(
                ssn.snap, jobs=dataclasses.replace(
                    ssn.snap.jobs, task_table=table))

    def task_pref_node(self, ssn) -> np.ndarray:
        """i32[T]: preferred node per pending task — the node already
        holding the most of its bucket (calcBucketScore's base term,
        topology.go:150-163, as a static per-cycle steer)."""
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        pref = np.full(T, -1, np.int32)
        for juid, jm in self.managers(ssn).items():
            job = ssn.cluster.jobs.get(juid)
            if job is None:
                continue
            for task in job.tasks.values():
                ti = ssn.maps.task_index.get(task.uid)
                if ti is None or task.node_name:
                    continue
                bucket = jm.get_bucket(task.uid)
                if bucket is None or not bucket.node:
                    continue
                best = max(sorted(bucket.node), key=lambda n: bucket.node[n])
                ni = ssn.maps.node_index.get(best, -1)
                pref[ti] = ni
        return pref
