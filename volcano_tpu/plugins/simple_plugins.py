"""Small policy plugins: priority, binpack, nodeorder, predicates,
conformance, overcommit, sla.

Each contributes static weights/gates that the Session folds into the
compiled passes:

- priority (pkg/scheduler/plugins/priority/priority.go:30-117): job/task
  priority ordering — the priority keys are always packed into the arrays;
  this plugin's presence is what turns them on in the reference conf, and it
  also vetoes preempting higher-or-equal-priority victims.
- binpack (pkg/scheduler/plugins/binpack/binpack.go:157-260): best-fit score
  weight from ``binpack.weight`` argument.
- nodeorder (pkg/scheduler/plugins/nodeorder/nodeorder.go:39-414): k8s scorer
  weights (leastrequested/mostrequested/balancedresource/tainttoleration).
- predicates (pkg/scheduler/plugins/predicates/predicates.go:42-288): enables
  the feasibility-mask conjunction (always compiled in; presence keeps
  conf-file parity).
- conformance (pkg/scheduler/plugins/conformance/conformance.go:30-68):
  vetoes eviction of kube-system / critical pods.
- overcommit (pkg/scheduler/plugins/overcommit/overcommit.go:28-124):
  enqueue admission with cluster overcommit factor.
- sla (pkg/scheduler/plugins/sla/sla.go:33-151): jobs waiting past
  ``sla-waiting-time`` are force-admitted/ordered first.
"""

from __future__ import annotations

import re
import time

import numpy as np

from .base import Plugin


class PriorityPlugin(Plugin):
    name = "priority"


class PredicatesPlugin(Plugin):
    name = "predicates"


class BinpackPlugin(Plugin):
    name = "binpack"

    def score_weights(self, ssn):
        return {"binpack_weight": self.arg_float("binpack.weight", 1.0)}


class NodeOrderPlugin(Plugin):
    name = "nodeorder"

    def score_weights(self, ssn):
        return {
            "least_allocated_weight":
                self.arg_float("leastrequested.weight", 1.0),
            "most_allocated_weight":
                self.arg_float("mostrequested.weight", 0.0),
            "balanced_weight":
                self.arg_float("balancedresource.weight", 1.0),
            "taint_prefer_weight":
                self.arg_float("tainttoleration.weight", 1.0),
            # InterPodAffinity batch scorer weight (nodeorder.go:104-140
            # podAffinityWeight; batch scoring dispatch nodeorder.go:273-306)
            "pod_affinity_weight":
                self.arg_float("podaffinity.weight", 1.0),
        }


#: k8s system priority classes (scheduling.SystemClusterCritical /
#: SystemNodeCritical, conformance.go:49-51)
SYSTEM_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")


class ConformancePlugin(Plugin):
    name = "conformance"

    def victim_veto(self, ssn) -> np.ndarray:
        """bool[T]: never evict kube-system tasks or pods in a system
        priority class (conformance.go:45-63 evictableFn skip rules)."""
        T = np.asarray(ssn.snap.tasks.status).shape[0]
        veto = np.zeros(T, bool)
        for job in ssn.cluster.jobs.values():
            for uid, task in job.tasks.items():
                ti = ssn.maps.task_index.get(uid)
                if ti is None:
                    continue
                if (task.namespace == "kube-system"
                        or task.priority_class in SYSTEM_PRIORITY_CLASSES):
                    veto[ti] = True
        return veto


class OvercommitPlugin(Plugin):
    name = "overcommit"

    def enqueue_gates(self, ssn):
        return {"enable_overcommit_gate": True,
                "overcommit_factor": self.arg_float("overcommit-factor", 1.2)}


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)([hms])")


def parse_duration(s: str) -> float:
    """'1h30m' / '300s' -> seconds (Go time.ParseDuration subset)."""
    total, pos = 0.0, 0
    for m in _DURATION_RE.finditer(s):
        total += float(m.group(1)) * {"h": 3600, "m": 60, "s": 1}[m.group(2)]
        pos = m.end()
    if pos == 0:
        raise ValueError(f"unparseable duration: {s!r}")
    return total


class SLAPlugin(Plugin):
    name = "sla"

    def _job_waiting_time(self, job):
        """Per-job sla-waiting-time annotation overrides the plugin's
        global argument (readJobWaitingTime, sla.go:57-66); None = no SLA
        for the job at all."""
        if job.sla_waiting_time:
            try:
                return parse_duration(str(job.sla_waiting_time))
            except ValueError:
                pass
        arg = self.arg("sla-waiting-time")
        if arg is None:
            return None
        try:
            return parse_duration(str(arg))
        except ValueError:
            return None

    def sla_waiting(self, ssn) -> np.ndarray:
        """bool[J]: jobs waiting past their SLA (the JobEnqueueableFn
        Permit, sla.go:133-145)."""
        J = np.asarray(ssn.snap.jobs.valid).shape[0]
        waiting = np.zeros(J, bool)
        now = ssn.now
        for uid, ji in ssn.maps.job_index.items():
            job = ssn.cluster.jobs.get(uid)
            if job is None:
                continue
            jwt = self._job_waiting_time(job)
            if jwt is not None and now - job.creation_timestamp >= jwt:
                waiting[ji] = True
        return waiting

    def job_deadline(self, ssn) -> np.ndarray:
        """f32[J] jobOrderFn key (sla.go:104-131): jobs WITH a waiting time
        sort first, earliest creation+jwt deadline wins. Encoded relative
        to now (f32 seconds); no-SLA jobs get +inf."""
        J = np.asarray(ssn.snap.jobs.valid).shape[0]
        deadline = np.full(J, np.inf, np.float32)
        for uid, ji in ssn.maps.job_index.items():
            job = ssn.cluster.jobs.get(uid)
            if job is None:
                continue
            jwt = self._job_waiting_time(job)
            if jwt is not None:
                deadline[ji] = np.float32(
                    job.creation_timestamp + jwt - ssn.now)
        return deadline
