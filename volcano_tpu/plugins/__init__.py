"""Policy plugins (reference: pkg/scheduler/plugins/factory.go:36-53)."""

from .base import Plugin
from .factory import (build_plugin, get_plugin_builder, load_custom_plugins,
                      register_plugin_builder, registered_plugins)

__all__ = ["Plugin", "build_plugin", "get_plugin_builder",
           "load_custom_plugins", "register_plugin_builder",
           "registered_plugins"]
