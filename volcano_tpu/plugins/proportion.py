"""Proportion plugin: weighted queue fair share.

Reference: pkg/scheduler/plugins/proportion/proportion.go:33-325. The
water-filling deserved computation runs as the compiled kernel
ops/fairshare.proportion_deserved; the Overused gate and queue share
ordering consume its output inside the allocate pass; the JobEnqueueable
gate runs in the enqueue pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import Plugin


class ProportionPlugin(Plugin):
    name = "proportion"

    def queue_deserved(self, ssn) -> np.ndarray:
        from ..ops.fairshare import proportion_deserved
        q = jax.tree.map(jnp.asarray, ssn.snap.queues)
        return np.asarray(proportion_deserved(
            q, jnp.asarray(ssn.snap.cluster_capacity)))

    def enqueue_gates(self, ssn):
        return {"enable_proportion_gate": True}
