"""Gang plugin: all-or-nothing admission of PodGroups.

Reference: pkg/scheduler/plugins/gang/gang.go:37-216. The core gang
semantics (JobReady/JobPipelined/JobStarving, ready-jobs-order-last) are
compiled into the allocate/preempt kernels; this class contributes the
victim-surplus vector used to veto evictions that would break a running gang
(gang.go:83-107) and writes PodGroup conditions at session close
(gang.go:158-216).
"""

from __future__ import annotations

import numpy as np

from ..api.types import (POD_GROUP_CONDITION_SCHEDULED,
                         POD_GROUP_CONDITION_UNSCHEDULABLE)
from .base import Plugin


class GangPlugin(Plugin):
    name = "gang"

    def on_session_close(self, ssn) -> None:
        """Write Scheduled/Unschedulable conditions onto jobs that were
        attempted this cycle (gang.go:158-216)."""
        for uid, ji in ssn.maps.job_index.items():
            job = ssn.cluster.jobs.get(uid)
            if job is None:
                continue
            if ssn.last_allocate is not None and bool(
                    np.asarray(ssn.last_allocate.job_attempted)[ji]):
                ready = bool(np.asarray(ssn.last_allocate.job_ready)[ji])
                cond = (POD_GROUP_CONDITION_SCHEDULED if ready
                        else POD_GROUP_CONDITION_UNSCHEDULABLE)
                job.job_fit_errors = "" if ready else (
                    f"{job.pending_task_num()}/{len(job.tasks)} tasks in gang "
                    f"unschedulable: job is not ready")
                ssn.conditions[uid] = cond
