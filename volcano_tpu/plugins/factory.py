"""Plugin registry (reference: pkg/scheduler/plugins/factory.go:36-53 and
framework/plugins.go:38-119 incl. custom-plugin loading)."""

from __future__ import annotations

import importlib.util
import os
from typing import Callable, Dict, Type

from ..framework.conf import PluginOption
from .base import Plugin
from .drf import DRFPlugin
from .gang import GangPlugin
from .proportion import ProportionPlugin
from .reservation import ReservationPlugin
from .simple_plugins import (BinpackPlugin, ConformancePlugin, NodeOrderPlugin,
                             OvercommitPlugin, PredicatesPlugin,
                             PriorityPlugin, SLAPlugin)
from .task_topology import TaskTopologyPlugin
from .tdm import TDMPlugin

_REGISTRY: Dict[str, Type[Plugin]] = {}


def register_plugin_builder(name: str, cls: Type[Plugin]) -> None:
    """Reference: RegisterPluginBuilder (framework/plugins.go:38)."""
    _REGISTRY[name] = cls


def get_plugin_builder(name: str) -> Type[Plugin]:
    if name not in _REGISTRY:
        raise KeyError(f"unknown plugin {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def build_plugin(option: PluginOption) -> Plugin:
    return get_plugin_builder(option.name)(option)


def registered_plugins():
    return sorted(_REGISTRY)


def load_custom_plugins(plugins_dir: str) -> int:
    """Load user plugin modules from a directory — the Python analog of the
    reference's Go ``plugin.Open`` .so loading (framework/plugins.go:62-99,
    docs/design/custom-plugin.md). Each ``*.py`` file must call
    ``register_plugin_builder`` at import time. Returns the number of modules
    loaded."""
    count = 0
    if not os.path.isdir(plugins_dir):
        return 0
    for fname in sorted(os.listdir(plugins_dir)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(plugins_dir, fname)
        spec = importlib.util.spec_from_file_location(
            f"volcano_tpu_custom_{fname[:-3]}", path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        count += 1
    return count


for _cls in (PriorityPlugin, GangPlugin, ConformancePlugin, DRFPlugin,
             ProportionPlugin, PredicatesPlugin, NodeOrderPlugin,
             BinpackPlugin, OvercommitPlugin, SLAPlugin, TDMPlugin,
             TaskTopologyPlugin, ReservationPlugin):
    register_plugin_builder(_cls.name, _cls)
