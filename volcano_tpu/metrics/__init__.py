"""Scheduler metrics (reference: pkg/scheduler/metrics/ — Prometheus
histograms/counters in subsystem ``volcano``, metrics.go:38-202).

Histogram buckets and metric names mirror the reference so dashboards port;
exposition is the Prometheus text format over a plain string (no client
library dependency).
"""

from .metrics import Metrics, METRICS

__all__ = ["Metrics", "METRICS"]
