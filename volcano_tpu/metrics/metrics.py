"""Metric registry with Prometheus text exposition.

Reference metric names (pkg/scheduler/metrics/metrics.go:38-202):
e2e_scheduling_latency_milliseconds, action_scheduling_latency_microseconds,
plugin_scheduling_latency_microseconds, task_scheduling_latency_milliseconds,
schedule_attempts_total, preemption_victims, unschedule_task_count; queue
gauges in queue.go:28-284.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple

_BUCKETS_MS = [5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000]


class Histogram:
    def __init__(self, buckets: List[float]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        self.total += value
        self.n += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[Tuple[str, str], float] = {}
        self.histograms: Dict[str, Histogram] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def set_gauge(self, name: str, label: str, value: float) -> None:
        with self._lock:
            self.gauges[(name, label)] = value

    def _hist(self, name: str) -> Histogram:
        if name not in self.histograms:
            self.histograms[name] = Histogram(_BUCKETS_MS)
        return self.histograms[name]

    def observe_cycle(self, seconds: float) -> None:
        """volcano_e2e_scheduling_latency_milliseconds (metrics.go:38-45)."""
        with self._lock:
            self._hist("e2e_scheduling_latency_milliseconds").observe(
                seconds * 1000)

    def observe_action(self, action: str, seconds: float) -> None:
        """volcano_action_scheduling_latency_microseconds (metrics.go:74-81)."""
        with self._lock:
            self._hist(f"action_scheduling_latency_microseconds"
                       f'{{action="{action}"}}').observe(seconds * 1e6)

    def observe_plugin(self, plugin: str, event: str, seconds: float) -> None:
        with self._lock:
            self._hist(f'plugin_scheduling_latency_microseconds'
                       f'{{plugin="{plugin}",event="{event}"}}').observe(
                seconds * 1e6)

    def update_queue_metrics(self, queue: str, allocated_cpu: float,
                             deserved_cpu: float, share: float) -> None:
        """queue_allocated/deserved/share gauges (metrics/queue.go:28-284)."""
        self.set_gauge("queue_allocated_milli_cpu", queue, allocated_cpu)
        self.set_gauge("queue_deserved_milli_cpu", queue, deserved_cpu)
        self.set_gauge("queue_share", queue, share)

    def exposition(self) -> str:
        """Prometheus text format (the /metrics endpoint payload)."""
        lines = []
        with self._lock:
            for name, v in sorted(self.counters.items()):
                lines.append(f"volcano_{name} {v}")
            for (name, label), v in sorted(self.gauges.items()):
                lines.append(f'volcano_{name}{{queue="{label}"}} {v}')
            for name, h in sorted(self.histograms.items()):
                base = name if "{" in name else name
                lines.append(f"volcano_{base}_count {h.n}")
                lines.append(f"volcano_{base}_sum {h.total}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


#: process-global registry, like the prometheus default registerer
METRICS = Metrics()
