"""Metric registry with Prometheus text exposition.

Reference metric families (pkg/scheduler/metrics/):
- metrics.go:38-202 — e2e_scheduling_latency_milliseconds,
  action/plugin_scheduling_latency_microseconds,
  task_scheduling_latency_milliseconds, schedule_attempts_total,
  preemption_victims, unschedule_task_count;
- queue.go:28-284 — per-queue allocated/request/deserved (milli_cpu +
  memory_bytes), share, weight, overused, pod-group phase counts;
- namespace.go:28-63 — namespace share/weight/weighted_share.

Histograms expose full cumulative bucket series (le labels + +Inf) so
reference-style latency quantile dashboards work against /metrics.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Mapping, Tuple, Union

_BUCKETS_MS = [5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000]

LabelsT = Union[str, Mapping[str, str], None]


def _label_str(labels: LabelsT, default_key: str = "queue") -> str:
    """Canonical `k="v",...` body (sorted) for a label set; a bare string
    keeps the historical queue-label shorthand."""
    if labels is None:
        return ""
    if isinstance(labels, str):
        return f'{default_key}="{labels}"'
    return ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))


class Histogram:
    def __init__(self, buckets: List[float]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        self.total += value
        self.n += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[Tuple[str, str], float] = {}
        self.histograms: Dict[Tuple[str, str], Histogram] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def set_gauge(self, name: str, labels: LabelsT, value: float) -> None:
        with self._lock:
            self.gauges[(name, _label_str(labels))] = value

    def _hist(self, name: str, labels: LabelsT = None) -> Histogram:
        key = (name, _label_str(labels))
        if key not in self.histograms:
            self.histograms[key] = Histogram(_BUCKETS_MS)
        return self.histograms[key]

    def observe_cycle(self, seconds: float) -> None:
        """volcano_e2e_scheduling_latency_milliseconds (metrics.go:38-45)."""
        with self._lock:
            self._hist("e2e_scheduling_latency_milliseconds").observe(
                seconds * 1000)

    def observe_action(self, action: str, seconds: float) -> None:
        """volcano_action_scheduling_latency_microseconds (metrics.go:74-81)."""
        with self._lock:
            self._hist("action_scheduling_latency_microseconds",
                       {"action": action}).observe(seconds * 1e6)

    def observe_plugin(self, plugin: str, event: str, seconds: float) -> None:
        """volcano_plugin_scheduling_latency_microseconds (metrics.go:65-72,
        recorded around OnSessionOpen/Close, framework.go:47-60)."""
        with self._lock:
            self._hist("plugin_scheduling_latency_microseconds",
                       {"plugin": plugin, "event": event}).observe(
                           seconds * 1e6)

    def observe_task_latency(self, seconds: float) -> None:
        """volcano_task_scheduling_latency_milliseconds (metrics.go:83-90)."""
        with self._lock:
            self._hist("task_scheduling_latency_milliseconds").observe(
                seconds * 1000)

    # ------------------------------------------------- gauge families
    def update_queue_family(self, queue: str, *, allocated_milli_cpu: float,
                            allocated_memory_bytes: float,
                            request_milli_cpu: float,
                            request_memory_bytes: float,
                            deserved_milli_cpu: float,
                            deserved_memory_bytes: float,
                            share: float, weight: float,
                            overused: bool,
                            pg_inqueue: int, pg_pending: int,
                            pg_running: int, pg_unknown: int) -> None:
        """The queue.go:28-284 gauge families for one queue."""
        g = self.set_gauge
        g("queue_allocated_milli_cpu", queue, allocated_milli_cpu)
        g("queue_allocated_memory_bytes", queue, allocated_memory_bytes)
        g("queue_request_milli_cpu", queue, request_milli_cpu)
        g("queue_request_memory_bytes", queue, request_memory_bytes)
        g("queue_deserved_milli_cpu", queue, deserved_milli_cpu)
        g("queue_deserved_memory_bytes", queue, deserved_memory_bytes)
        g("queue_share", queue, share)
        g("queue_weight", queue, weight)
        g("queue_overused", queue, 1.0 if overused else 0.0)
        g("queue_pod_group_inqueue_count", queue, pg_inqueue)
        g("queue_pod_group_pending_count", queue, pg_pending)
        g("queue_pod_group_running_count", queue, pg_running)
        g("queue_pod_group_unknown_count", queue, pg_unknown)

    def update_namespace_family(self, namespace: str, share: float,
                                weight: float) -> None:
        """namespace.go:28-63: share, weight, weighted share."""
        labels = {"namespace_name": namespace}
        self.set_gauge("namespace_share", labels, share)
        self.set_gauge("namespace_weight", labels, weight)
        self.set_gauge("namespace_weighted_share", labels,
                       share / weight if weight else share)

    def update_queue_metrics(self, queue: str, allocated_cpu: float,
                             deserved_cpu: float, share: float) -> None:
        """Back-compat shim over the full family (queue.go:28-284)."""
        self.set_gauge("queue_allocated_milli_cpu", queue, allocated_cpu)
        self.set_gauge("queue_deserved_milli_cpu", queue, deserved_cpu)
        self.set_gauge("queue_share", queue, share)

    def exposition(self) -> str:
        """Prometheus text format (the /metrics endpoint payload), with
        full cumulative histogram bucket series."""
        lines = []
        with self._lock:
            for name, v in sorted(self.counters.items()):
                lines.append(f"volcano_{name} {v}")
            for (name, labels), v in sorted(self.gauges.items()):
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"volcano_{name}{suffix} {v}")
            for (name, labels), h in sorted(self.histograms.items()):
                prefix = f"{labels}," if labels else ""
                cum = h.cumulative()
                for b, c in zip(h.buckets, cum):
                    lines.append(
                        f'volcano_{name}_bucket{{{prefix}le="{b}"}} {c}')
                lines.append(
                    f'volcano_{name}_bucket{{{prefix}le="+Inf"}} {cum[-1]}')
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"volcano_{name}_count{suffix} {h.n}")
                lines.append(f"volcano_{name}_sum{suffix} {h.total}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


#: process-global registry, like the prometheus default registerer
METRICS = Metrics()
