"""Metric registry with Prometheus text exposition.

Reference metric families (pkg/scheduler/metrics/):
- metrics.go:38-202 — e2e_scheduling_latency_milliseconds,
  action/plugin_scheduling_latency_microseconds,
  task_scheduling_latency_milliseconds, schedule_attempts_total,
  preemption_victims, unschedule_task_count;
- queue.go:28-284 — per-queue allocated/request/deserved (milli_cpu +
  memory_bytes), share, weight, overused, pod-group phase counts;
- namespace.go:28-63 — namespace share/weight/weighted_share.

Histograms expose full cumulative bucket series (le labels + +Inf) so
reference-style latency quantile dashboards work against /metrics.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Mapping, Tuple, Union

#: millisecond-valued histograms (e2e / task scheduling latency)
_BUCKETS_MS = [5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000]

#: microsecond-valued histograms (action / plugin latency). Every
#: histogram used to share the millisecond series above, so any action
#: slower than 10 ms (= 10000 us) fell straight into +Inf — per-metric
#: bucket sets fix the mismatch (50 us .. 10 s, roughly the reference's
#: prometheus.ExponentialBuckets(5, 2, ...) span).
_BUCKETS_US = [50, 100, 250, 500, 1000, 2500, 5000, 10000, 25000, 50000,
               100000, 250000, 500000, 1000000, 2500000, 5000000, 10000000]

LabelsT = Union[str, Mapping[str, str], None]

#: `# HELP` text per metric base name; names without an entry get a
#: generated default so strict parsers always see HELP/TYPE pairs.
_HELP = {
    "e2e_scheduling_latency_milliseconds":
        "E2E scheduling latency in ms (scheduling algorithm + binding)",
    "action_scheduling_latency_microseconds":
        "Action scheduling latency in microseconds",
    "plugin_scheduling_latency_microseconds":
        "Plugin scheduling latency in microseconds",
    "task_scheduling_latency_milliseconds":
        "Task scheduling latency in milliseconds",
    "schedule_attempts_total":
        "Number of attempts to schedule pods, by result",
    "unschedule_task_count":
        "Number of tasks that could not be scheduled, by reason",
    "cycle_predicate_rejections":
        "In-graph per-predicate-family node rejection counts",
    "wave_commits_total":
        "Tasks committed by wavefront placement waves (wave_width > 1)",
    "wave_truncations_total":
        "Wavefront waves cut short by the in-graph conflict rule "
        "(pre-wave candidate list exhausted by same-wave commits)",
    "wave_replays_total":
        "Task attempts deferred to the next wave by a truncation",
    "wave_commit_ratio":
        "Last cycle's wavefront commit efficiency: commits / (commits + "
        "replays); 1.0 = every wave slot committed first try",
    "jit_traces": "Times each jitted cycle entry point was traced",
    "jit_calls": "Times each jitted cycle entry point was called",
    "jit_cache_hits": "Jit calls served from the compile cache",
    "resident_digest_mismatch_total":
        "Device-resident buffer integrity digest mismatches vs the host "
        "mirror (each one triggered a full re-fuse recovery)",
    "cycle_recoveries_total":
        "Scheduling cycles recovered in place, by reason and mode "
        "(refuse / sync / cpu_oracle)",
    "cycle_faults_total":
        "Faults absorbed by the cycle runtime, by stage",
    "cycle_dropped_total":
        "Cycles retired with no decisions after recovery failed",
    "resync_dead_letter_total":
        "Bind/evict intents that exhausted resync retries and moved to "
        "the dead-letter list (never dropped silently)",
    "degradation_level":
        "Current degradation ladder rung: 0 pipelined, 1 sync, "
        "2 elastic-mesh (shrunk sharded), 3 cpu-oracle",
    "mesh_shrink_total":
        "Elastic-mesh shrinks: device quarantines that halved the "
        "serving-width cap and rebuilt the mesh over the survivors, "
        "by reason",
    "mesh_regrow_total":
        "Elastic-mesh probation regrows: cap lifted a pow2 step back "
        "toward the full mesh after a quiet probation interval",
    "mesh_width":
        "Serving mesh width (devices) observed at the last finished "
        "sharded cycle",
    "sidecar_reconnects_total":
        "Sidecar client reconnects after a socket failure",
    "sidecar_replayed_rounds_total":
        "VCRQ rounds served from the idempotent replay cache",
    "sidecar_epoch_restored_total":
        "Mid-stream rounds rejected with ERR_EPOCH_RESTORED after a "
        "server restart (side=server) and client re-primes that followed "
        "(side=client)",
    # crash-consistent checkpoint/restore (runtime/checkpoint.py)
    "checkpoint_write_total":
        "Crash-consistent checkpoints written (atomic tmp+fsync+rename), "
        "by kind (scheduler / sidecar)",
    "checkpoint_restore_total":
        "Restore attempts by outcome: restored (warm), cold (no "
        "checkpoint), fallback (corrupt / version-skewed / mismatched "
        "checkpoint degraded to a fresh-fuse cold start)",
    "checkpoint_mirror_invalid_total":
        "Checkpointed host mirrors dropped at restore because their "
        "integrity digest no longer matched (cold re-fuse instead)",
    "checkpoint_warm_refuse_total":
        "Resident states re-fused warm from a restored checkpoint mirror "
        "(the delta path survived the restart)",
    "crash_loop_restarts_total":
        "Supervised serve-loop restarts after a crash (capped backoff)",
    "resync_redrive_total":
        "Dead-letter resync intents re-driven back to pending after a "
        "successful restore",
    "span_phase_ms":
        "Host span duration quantiles per cycle phase (ring-buffered "
        "p50/p95/p99 from telemetry.spans — the SLO latency surface)",
    "pipeline_overlap_fraction":
        "Fraction of the in-flight device window covered by non-blocked "
        "host work (telemetry.spans occupancy; ~0 when synchronous)",
    "pipeline_bubble_ms":
        "In-flight device window time the host spent idle or blocked "
        "(the pipeline bubble the deep-async item must shrink)",
    # scheduling-quality scorecards (volcano_tpu/scenarios): one gauge set
    # per scenario run, the same numbers /api/scenarios and the bench
    # `scenarios` block carry
    "quality_makespan_cycles":
        "Scenario makespan in virtual cycles (first arrival to last "
        "job completion)",
    "quality_drf_share_error":
        "Mean per-cycle DRF share error: |allocated - deserved| summed "
        "over queues, normalized by cluster capacity (0 = fair)",
    "quality_node_utilization":
        "Mean per-cycle allocated/capacity cpu fraction over the "
        "scenario run",
    "quality_preemption_churn_total":
        "Evictions the scenario run produced (preempt + reclaim churn)",
    "quality_queue_wait_cycles":
        "Queue-wait quantiles in virtual cycles (arrival to first bind), "
        "nearest-rank p50/p95/p99",
    "quality_jobs_completed":
        "Jobs that ran to completion inside the scenario horizon",
    "quality_drift_failures":
        "Soak-mode CPU-oracle drift spot-checks where compiled decisions "
        "diverged from the oracle (must stay 0)",
    # high availability: leader election, lease fencing, checkpoint
    # streaming, warm-standby failover (runtime/{leader,replication}.py)
    "leader_transitions_total":
        "Leadership transitions observed by this scheduler, by "
        "destination role (to=leader / to=follower)",
    "is_leader":
        "1 while this scheduler holds the leader lease, else 0",
    "fenced_writes_rejected_total":
        "Bind/evict writes rejected because they carried a superseded "
        "lease-generation fencing token (a deposed leader's late "
        "writes), by kind",
    "replication_envelopes_total":
        "Checkpoint-stream envelopes by delivery result (applied / lost "
        "/ resync_gap / resync_invalid / resync_applied ...)",
    "replication_mirror_invalid_total":
        "Streamed mirror records the standby refused because their "
        "integrity digest did not match (never adopted)",
    "replication_lag_seq":
        "Envelopes the warm standby lags behind the leader's stream "
        "(0 in the steady state)",
    "failover_promotions_total":
        "Warm-standby promotions by ladder rung: warm (replicated "
        "state + mirrors adopted), cold (nothing replicated), fallback "
        "(conf-fingerprint mismatch, fresh cold start)",
    "sidecar_failovers_total":
        "Sidecar client reconnects that landed on a DIFFERENT endpoint "
        "of the replica set (each costs one pipeline re-prime)",
    "sidecar_not_leader_total":
        "Sidecar rounds rejected with ERR_NOT_LEADER because their "
        "fencing token was superseded",
    # multi-tenant fleet runtime (volcano_tpu/fleet)
    "fleet_tenants":
        "Tenants currently admitted to the fleet scheduler",
    "fleet_cycles_total":
        "Fleet serving cycles completed, by tenant",
    "fleet_admissions_total":
        "Fleet admission-control events, by event (admit / evict)",
    "fleet_tenant_degradation":
        "Per-tenant degradation ladder rung: 0 batched fleet path, "
        "1 sync retry, 2 cpu-oracle",
    "sidecar_replay_evictions_total":
        "Per-tenant sidecar replay-cache epochs evicted by the bounded "
        "LRU (VOLCANO_SIDECAR_EPOCH_CAP)",
    # per-cycle decision readback gauges (telemetry.publish)
    "cycle_tasks_allocated":
        "Tasks bound to nodes by the last scheduling cycle",
    "cycle_tasks_pipelined":
        "Tasks the last cycle carried as pipelined (in-flight) work",
    "cycle_gang_discarded_tasks":
        "Tasks discarded by the in-graph gang (minAvailable) filter in "
        "the last cycle",
    "cycle_argmax_ties":
        "Node-score argmax ties broken by index order in the last cycle "
        "(a proxy for score-plateau sensitivity)",
    "cycle_rounds":
        "Scheduling rounds the last cycle's fixed-trip scan executed",
    "cycle_pops":
        "Priority-queue pops the last cycle performed in-graph",
    "cycle_dyn_launches":
        "Segments the dynamic early-stop cycle launched last cycle",
    "cycle_dyn_early_stops":
        "Dynamic cycles that stopped before the worst-case trip count "
        "because the queue drained",
    "cycle_replays_total":
        "Wavefront task attempts replayed into a later wave by the "
        "host-side runtime (cumulative across cycles)",
    "cycle_upload_bytes":
        "Host-to-device bytes uploaded by the last delta fuse (the "
        "O(changed rows) payload, not the full snapshot)",
    "sharded_resharding_copies_total":
        "Resident buffers that left a sharded cycle with a different "
        "sharding than they entered (must stay 0: each one is a "
        "per-iteration resharding copy)",
    # DRF / queue scorecard gauges (update_queue_family), mirroring
    # upstream volcano's queue_* exposition names
    "queue_allocated_milli_cpu":
        "CPU milli-cores currently allocated to the queue",
    "queue_allocated_memory_bytes":
        "Memory bytes currently allocated to the queue",
    "queue_request_milli_cpu":
        "CPU milli-cores requested by the queue's pending+running tasks",
    "queue_request_memory_bytes":
        "Memory bytes requested by the queue's pending+running tasks",
    "queue_deserved_milli_cpu":
        "CPU milli-cores the DRF plugin computed as the queue's "
        "deserved share",
    "queue_deserved_memory_bytes":
        "Memory bytes the DRF plugin computed as the queue's deserved "
        "share",
    "queue_share":
        "Dominant-resource share of the queue (allocated / deserved)",
    "queue_weight":
        "Configured scheduling weight of the queue",
    "queue_overused":
        "1 if the queue's share exceeds its deserved allocation, else 0",
    "queue_pod_group_inqueue_count":
        "PodGroups of the queue in Inqueue state",
    "queue_pod_group_pending_count":
        "PodGroups of the queue in Pending state",
    "queue_pod_group_running_count":
        "PodGroups of the queue in Running state",
    "queue_pod_group_unknown_count":
        "PodGroups of the queue in Unknown state",
    "namespace_share":
        "Dominant-resource share of the namespace",
    "namespace_weight":
        "Configured scheduling weight of the namespace",
    "namespace_weighted_share":
        "Namespace share divided by its weight (the value proportion "
        "plugins compare across namespaces)",
    # fleet resync / dispatch counters (fleet/scheduler.py)
    "resync_retried":
        "Bind/evict intents re-driven by the fleet resync loop",
    "resync_succeeded":
        "Bind/evict intents the fleet resync loop confirmed applied",
    "resync_dropped":
        "Bind/evict intents the fleet resync loop abandoned after "
        "exhausting retries",
    "resync_tasks":
        "Tasks touched by the last fleet resync sweep",
    "schedule_attempts":
        "Fleet per-tenant schedule attempts, by result",
}


def _label_str(labels: LabelsT, default_key: str = "queue") -> str:
    """Canonical `k="v",...` body (sorted) for a label set; a bare string
    keeps the historical queue-label shorthand."""
    if labels is None:
        return ""
    if isinstance(labels, str):
        return f'{default_key}="{labels}"'
    return ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))


class Histogram:
    def __init__(self, buckets: List[float]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        self.total += value
        self.n += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        #: (name, label_str) -> value. Bare .inc(name) keys on (name, "")
        #: so existing callers are unchanged; labeled counters carry the
        #: reference's label sets (schedule_attempts_total{result=...},
        #: unschedule_task_count{reason=...}).
        self.counters: Dict[Tuple[str, str], float] = defaultdict(float)
        self.gauges: Dict[Tuple[str, str], float] = {}
        self.histograms: Dict[Tuple[str, str], Histogram] = {}

    def inc(self, name: str, value: float = 1.0,
            labels: LabelsT = None) -> None:
        with self._lock:
            self.counters[(name, _label_str(labels))] += value

    def counter_value(self, name: str, labels: LabelsT = None) -> float:
        """Read a counter (0.0 when never incremented)."""
        with self._lock:
            return self.counters.get((name, _label_str(labels)), 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter across ALL label sets (0.0 when absent)."""
        with self._lock:
            return sum(v for (n, _ls), v in self.counters.items()
                       if n == name)

    def set_gauge(self, name: str, labels: LabelsT, value: float) -> None:
        with self._lock:
            self.gauges[(name, _label_str(labels))] = value

    def _hist(self, name: str, labels: LabelsT = None,
              buckets=None) -> Histogram:
        key = (name, _label_str(labels))
        if key not in self.histograms:
            self.histograms[key] = Histogram(buckets or _BUCKETS_MS)
        return self.histograms[key]

    def observe_cycle(self, seconds: float) -> None:
        """volcano_e2e_scheduling_latency_milliseconds (metrics.go:38-45)."""
        with self._lock:
            self._hist("e2e_scheduling_latency_milliseconds").observe(
                seconds * 1000)

    def observe_action(self, action: str, seconds: float) -> None:
        """volcano_action_scheduling_latency_microseconds (metrics.go:74-81).
        Microsecond values get the microsecond bucket series (_BUCKETS_US);
        the former shared millisecond buckets put anything over 10 ms in
        +Inf."""
        with self._lock:
            self._hist("action_scheduling_latency_microseconds",
                       {"action": action},
                       buckets=_BUCKETS_US).observe(seconds * 1e6)

    def observe_plugin(self, plugin: str, event: str, seconds: float) -> None:
        """volcano_plugin_scheduling_latency_microseconds (metrics.go:65-72,
        recorded around OnSessionOpen/Close, framework.go:47-60)."""
        with self._lock:
            self._hist("plugin_scheduling_latency_microseconds",
                       {"plugin": plugin, "event": event},
                       buckets=_BUCKETS_US).observe(seconds * 1e6)

    def observe_task_latency(self, seconds: float) -> None:
        """volcano_task_scheduling_latency_milliseconds (metrics.go:83-90)."""
        with self._lock:
            self._hist("task_scheduling_latency_milliseconds").observe(
                seconds * 1000)

    # ------------------------------------------------- gauge families
    def update_queue_family(self, queue: str, *, allocated_milli_cpu: float,
                            allocated_memory_bytes: float,
                            request_milli_cpu: float,
                            request_memory_bytes: float,
                            deserved_milli_cpu: float,
                            deserved_memory_bytes: float,
                            share: float, weight: float,
                            overused: bool,
                            pg_inqueue: int, pg_pending: int,
                            pg_running: int, pg_unknown: int) -> None:
        """The queue.go:28-284 gauge families for one queue."""
        g = self.set_gauge
        g("queue_allocated_milli_cpu", queue, allocated_milli_cpu)
        g("queue_allocated_memory_bytes", queue, allocated_memory_bytes)
        g("queue_request_milli_cpu", queue, request_milli_cpu)
        g("queue_request_memory_bytes", queue, request_memory_bytes)
        g("queue_deserved_milli_cpu", queue, deserved_milli_cpu)
        g("queue_deserved_memory_bytes", queue, deserved_memory_bytes)
        g("queue_share", queue, share)
        g("queue_weight", queue, weight)
        g("queue_overused", queue, 1.0 if overused else 0.0)
        g("queue_pod_group_inqueue_count", queue, pg_inqueue)
        g("queue_pod_group_pending_count", queue, pg_pending)
        g("queue_pod_group_running_count", queue, pg_running)
        g("queue_pod_group_unknown_count", queue, pg_unknown)

    def update_namespace_family(self, namespace: str, share: float,
                                weight: float) -> None:
        """namespace.go:28-63: share, weight, weighted share."""
        labels = {"namespace_name": namespace}
        self.set_gauge("namespace_share", labels, share)
        self.set_gauge("namespace_weight", labels, weight)
        self.set_gauge("namespace_weighted_share", labels,
                       share / weight if weight else share)

    def update_queue_metrics(self, queue: str, allocated_cpu: float,
                             deserved_cpu: float, share: float) -> None:
        """Back-compat shim over the full family (queue.go:28-284)."""
        self.set_gauge("queue_allocated_milli_cpu", queue, allocated_cpu)
        self.set_gauge("queue_deserved_milli_cpu", queue, deserved_cpu)
        self.set_gauge("queue_share", queue, share)

    @staticmethod
    def _meta_lines(lines, seen, name: str, mtype: str) -> None:
        """Emit `# HELP` / `# TYPE` once per metric base name, ahead of its
        first sample — strict Prometheus parsers require the pair; the
        sample line format itself is unchanged."""
        if name in seen:
            return
        seen.add(name)
        help_text = _HELP.get(name, name.replace("_", " "))
        lines.append(f"# HELP volcano_{name} {help_text}")
        lines.append(f"# TYPE volcano_{name} {mtype}")

    def exposition(self) -> str:
        """Prometheus text format (the /metrics endpoint payload), with
        `# HELP` / `# TYPE` metadata and full cumulative histogram bucket
        series."""
        lines = []
        seen = set()
        with self._lock:
            for (name, labels), v in sorted(self.counters.items()):
                self._meta_lines(lines, seen, name, "counter")
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"volcano_{name}{suffix} {v}")
            for (name, labels), v in sorted(self.gauges.items()):
                self._meta_lines(lines, seen, name, "gauge")
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"volcano_{name}{suffix} {v}")
            for (name, labels), h in sorted(self.histograms.items()):
                self._meta_lines(lines, seen, name, "histogram")
                prefix = f"{labels}," if labels else ""
                cum = h.cumulative()
                for b, c in zip(h.buckets, cum):
                    lines.append(
                        f'volcano_{name}_bucket{{{prefix}le="{b}"}} {c}')
                lines.append(
                    f'volcano_{name}_bucket{{{prefix}le="+Inf"}} {cum[-1]}')
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"volcano_{name}_count{suffix} {h.n}")
                lines.append(f"volcano_{name}_sum{suffix} {h.total}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


#: process-global registry, like the prometheus default registerer
METRICS = Metrics()
