"""TenantPool: many tenants' resident state through one compiled cycle.

The single-tenant delta path (ops/fused_io.DeltaKernel) holds one
(snap, extras) tree resident on the device and ships O(changed elements)
per cycle. A fleet of B tenants run that way costs B dispatches per fleet
cycle — the per-dispatch latency dominates long before the FLOPs do. This
module batches them: tenants whose derived (AllocateConfig, shape
signature) match share a SHAPE BUCKET, each bucket owns ONE
:class:`FleetDeltaKernel` whose jitted entry stacks the three group
buffers along a leading tenant axis, scatters every tenant's packed delta
in one flat scatter, and vmaps the allocate cycle over the tenant axis —
B same-bucket tenants cost one dispatch.

Compile discipline (the PR 4 delta-bucket rule lifted to the tenant
axis): the tenant axis pads to a power of two (``pow2_bucket(B, 1)``), so
admission/eviction retraces a bucket O(log B) times, never per tenant;
delta sizes pad with the same pow2 rule as the flat kernel. A tenant
joining or changing bucket restacks — and possibly retraces — ONLY its
own bucket: kernels are per-bucket objects with per-bucket jit entries
(``fleet_cycle/<key>``), so the trace counters prove one compile per
bucket, not per tenant.

Isolation: the vmapped cycle cannot mix tenant rows by construction (vmap
maps every operation over the leading axis), the per-tenant integrity
digest rides each tenant's row of the packed readback, and the graphcheck
``fleet`` family (analysis/fleet.py) audits the batched entry — no
callbacks, every decision output carries the tenant axis, and a
value-level probe proves perturbing one tenant's inputs cannot move
another tenant's decisions.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos.inject import seam
from ..metrics import METRICS
from ..ops.fused_io import (_GROUPS, _TARGETS, DIGEST_WORDS, _device_digest,
                            _pad_delta, _shape_key, delta_bucket,
                            donation_for_backend, fuse_into, fuse_spec,
                            group_sizes, host_digest, make_unfuse,
                            pow2_bucket)
from ..telemetry import spans as _spans

#: planted cross-tenant leak for the graphcheck family-10 proof: tests set
#: this True before building a kernel and the batched entry compiles in a
#: deliberate reduction over the tenant axis — the fleet check must fire.
#: NEVER set outside tests.
_LEAK_FOR_TESTS = False


def normalize_config(cfg, sharding: bool = False):
    """The bucket-key form of a tenant's derived AllocateConfig.

    ``telemetry`` and ``use_pallas`` are decision-neutral backend/readout
    knobs (the repo's equality suites pin scan == pallas == telemetry-on
    decisions); normalizing them lets tenants that differ only there share
    a bucket. On the unsharded batched path ``use_pallas`` is stripped to
    the explicit force-scan value (False, not None: None means
    auto-detect, which would pick the kernel on TPU) — the
    vmap-over-tenant-axis transform composes with lax control flow, not
    with a pallas_call launch. With ``sharding`` active the knob STAYS in
    the key: the sharded cycle dispatches per kernel mode (scan vs the
    shard-local candidate launch), so tenants split buckets on it instead
    of silently sharing a scan program. Everything decision-relevant
    (weights, gates, derived batching) stays in the key either way, so
    tenants with different policies never share a compiled program.

    ``wave_width`` (ISSUE 16) deliberately STAYS in the key despite being
    decision-neutral: W > 1 swaps the inner section scan for the
    wavefront while_loop, a different program shape, and the wave
    telemetry counters are only meaningful per width — sharing a bucket
    across widths would silently serve one width's program to both.
    """
    if sharding:
        return dataclasses.replace(cfg, telemetry=False)
    return dataclasses.replace(cfg, telemetry=False, use_pallas=False)


def _serving_mesh_width(tree) -> int:
    """The mesh width the health registry currently admits for this
    tree's node axis (parallel/sharding.mesh_for_nodes — healthy devices,
    shrink cap, pow2 divisibility). The mesh object is cached by device
    tuple, so this is a dict lookup on the steady path."""
    from ..parallel.sharding import mesh_for_nodes
    n_nodes = int(np.asarray(jax.tree.leaves(tree[0].nodes)[0]).shape[0])
    return int(mesh_for_nodes(n_nodes).devices.size)


def bucket_key(cfg, tree, sharding: bool = False) -> tuple:
    """Shape-bucket identity: the normalized config + the exact per-leaf
    (shape, dtype) signature — the same key construction the single-tenant
    delta cache uses (ops/fused_io._shape_key), so fleet buckets and
    single-tenant shape buckets cannot drift.

    Sharded tenants additionally key on the CURRENT serving mesh width
    (ISSUE 20): when the device-health registry quarantines a device or
    a probation regrow lifts the cap, the next ``place()`` re-buckets the
    tenant instead of serving it from a bucket stacked for the old mesh —
    the fleet analog of the Scheduler's drop-residency-and-refuse."""
    key = _shape_key(tree, normalize_config(cfg, sharding=sharding))
    if sharding:
        key = key + (("mesh_width", _serving_mesh_width(tree)),)
    return key


def _entry_name(key: tuple, width: int) -> str:
    """Stable per-(bucket, width) jit entry name for the trace counters:
    ``counts()['fleet_cycle/<h>w<width>']['traces']`` staying at 1 while
    B tenants cycle is the one-compile-per-bucket proof."""
    h = hashlib.sha256(repr(key).encode()).hexdigest()[:8]
    return f"fleet_cycle/{h}w{width}"


class TenantResident:
    """Per-tenant host half of a bucket's stacked residency: the mirror of
    this tenant's row of device truth, a ping-pong scratch, and the upload
    accounting the flight recorder snapshots. The device rows themselves
    are stacked per bucket (:class:`_Bucket`) — that is the point."""

    __slots__ = ("mirror", "scratch", "warm_mirror", "full_cycles",
                 "delta_cycles", "last_kind", "last_upload_bytes",
                 "full_upload_bytes")

    def __init__(self):
        self.mirror: Optional[tuple] = None
        self.scratch: Optional[tuple] = None
        #: digest-verified pre-restart mirror (fleet checkpoint restore):
        #: adopted as this tenant's row at the next stack so the first
        #: cycle ships a delta instead of contributing to a cold restack
        self.warm_mirror: Optional[tuple] = None
        self.full_cycles = 0
        self.delta_cycles = 0
        self.last_kind: Optional[str] = None
        self.last_upload_bytes = 0
        self.full_upload_bytes = 0


class FleetDeltaKernel:
    """Compiled batched delta-update + cycle entry over tenant-stacked
    resident buffers.

    The jitted entry takes the three stacked residents ``(B, n_g)``
    (donated on accelerators, like the flat kernel) plus per-group packed
    ``(indices, values)`` deltas whose indices are GLOBAL — flattened
    ``tenant_row * n_g + element`` — so every tenant's delta applies in
    one flat scatter, then vmaps the cycle over the tenant axis:

        (fbuf', ibuf', bbuf', packed) = fn(fbuf, ibuf, bbuf,
                                           fidx, fvals, iidx, ivals,
                                           bidx, bvals)

    ``packed`` is ``(B, P [+ 3])``: each tenant's packed decisions, with
    its own integrity digest words computed over its own rows — verified
    per tenant against that tenant's host mirror, exactly the flat
    kernel's formula.
    """

    def __init__(self, cycle_fn, example_tree, width: int,
                 entry: str = "fleet_cycle", integrity: bool = True):
        self.treedef, self.spec = fuse_spec(example_tree)
        self.sizes = group_sizes(self.spec)
        self.width = int(width)
        self.entry = entry
        self.digest_words = DIGEST_WORDS if integrity else 0
        self.donate_argnums = donation_for_backend()
        unfuse = make_unfuse(self.treedef, self.spec)
        sizes = self.sizes

        def _one(fbuf, ibuf, bbuf):
            args = unfuse(fbuf, ibuf, bbuf)
            packed = cycle_fn(*args).packed_decisions()
            if integrity:
                packed = jnp.concatenate(
                    [packed, _device_digest(fbuf, ibuf, bbuf)])
            return packed

        leak = _LEAK_FOR_TESTS

        def _batched_cycle(fbuf, ibuf, bbuf,
                           fidx, fvals, iidx, ivals, bidx, bvals):
            B = fbuf.shape[0]
            # one flat scatter per group applies EVERY tenant's delta:
            # indices are global (row * n + element), the stacked analog
            # of the flat kernel's buf.at[idx].set(vals)
            fbuf = fbuf.reshape(B * sizes[0]).at[fidx].set(
                fvals).reshape(B, sizes[0])
            ibuf = ibuf.reshape(B * sizes[1]).at[iidx].set(
                ivals).reshape(B, sizes[1])
            bbuf = bbuf.reshape(B * sizes[2]).at[bidx].set(
                bvals).reshape(B, sizes[2])
            packed = jax.vmap(_one)(fbuf, ibuf, bbuf)
            if leak:
                # test-planted cross-tenant data flow (see _LEAK_FOR_TESTS)
                mix = (jnp.sum(ibuf, dtype=jnp.int32) % jnp.int32(7)
                       if sizes[1] else jnp.int32(0))
                packed = packed.at[:, 0].add(mix)
            return fbuf, ibuf, bbuf, packed

        from ..telemetry import counted_jit
        self._fn = counted_jit(_batched_cycle, entry,
                               donate_argnums=self.donate_argnums)

    # ---------------------------------------------------------- graphcheck
    @property
    def traceable(self):
        """The raw (unjitted) batched body, for jaxpr-level analysis
        (graphcheck ``fleet`` family)."""
        return self._fn.__wrapped__

    def example_batched_args(self, bucket: int = 0):
        """Concrete example inputs for tracing: stacked zero residents
        plus ``bucket``-sized no-op deltas per non-empty group."""
        args = [np.zeros((self.width, n), _TARGETS[g])
                for g, n in zip(_GROUPS, self.sizes)]
        for g, n in zip(_GROUPS, self.sizes):
            b = bucket if n else 0
            args.append(np.zeros(b, np.int32))
            args.append(np.zeros(b, _TARGETS[g]))
        return tuple(args)


class _Bucket:
    """One shape bucket's live state: the batched kernel (built lazily at
    the current pow2 width), the ordered member residents, and the stacked
    device handles."""

    def __init__(self, key: tuple):
        self.key = key
        self.kernel: Optional[FleetDeltaKernel] = None
        self.members: Dict[str, TenantResident] = {}
        #: tenant order the CURRENT device stack was built for (row r =
        #: stacked_names[r]); any membership change forces a restack
        self.stacked_names: Tuple[str, ...] = ()
        self.device: Optional[tuple] = None
        self.retiring: tuple = ()
        #: structural epoch: bumped on every membership/width change — the
        #: admission/eviction observability hook (a bump never touches
        #: OTHER buckets' kernels, which is the no-cross-retrace claim)
        self.epoch = 0

    @property
    def width(self) -> int:
        return self.kernel.width if self.kernel is not None else 0


def _invalidate(handles) -> None:
    """Kill retired device handles (the flat kernel's invalidation
    contract: a host re-read of a consumed resident fails fast)."""
    for h in handles or ():
        try:
            if not h.is_deleted():
                h.delete()
        except Exception:
            pass


class TenantPool:
    """All buckets' resident state plus the batched run loop.

    The pool is the fleet analog of the Session's ``_resident`` dict: the
    kernels are stateless compiled programs, the residency (stacked device
    buffers + per-tenant mirrors) lives here, owned by the fleet
    scheduler that holds the pool.
    """

    def __init__(self, integrity: bool = True):
        self.integrity = integrity
        self.buckets: Dict[tuple, _Bucket] = {}
        #: tenant name -> bucket key currently holding its residency
        self.placement: Dict[str, tuple] = {}

    # ------------------------------------------------------------ placement
    def bucket_of(self, name: str) -> Optional[_Bucket]:
        key = self.placement.get(name)
        return self.buckets.get(key) if key is not None else None

    def place(self, name: str, cfg, tree, sharding: bool = False) -> _Bucket:
        """Route a tenant to its shape bucket for this cycle, migrating
        its residency if the derived key changed (a structural cluster
        change moved it to another bucket — only the two touched buckets
        restack; every other bucket's kernel and residents are
        untouched). ``sharding`` mirrors the tenant conf's flag: sharded
        tenants split buckets on ``use_pallas`` (see normalize_config)."""
        key = bucket_key(cfg, tree, sharding=sharding)
        old = self.placement.get(name)
        if old is not None and old != key:
            self.evict(name)
        bucket = self.buckets.get(key)
        if bucket is None:
            bucket = self.buckets[key] = _Bucket(key)
        if name not in bucket.members:
            bucket.members[name] = TenantResident()
            bucket.stacked_names = ()   # force restack at next run
            bucket.epoch += 1
        self.placement[name] = key
        return bucket

    def evict(self, name: str) -> None:
        """Drop a tenant's residency (fleet eviction or bucket change).
        The bucket restacks at its next run; an emptied bucket drops its
        device handles immediately."""
        key = self.placement.pop(name, None)
        bucket = self.buckets.get(key) if key is not None else None
        if bucket is None:
            return
        bucket.members.pop(name, None)
        bucket.stacked_names = ()
        bucket.epoch += 1
        if not bucket.members:
            _invalidate(bucket.retiring)
            _invalidate(bucket.device or ())
            bucket.device = None
            bucket.retiring = ()

    # ------------------------------------------------------------- running
    def run_bucket(self, bucket: _Bucket, cycle_fn_builder, cfg,
                   items: List[Tuple[str, object]],
                   force_full: bool = False):
        """One batched cycle for a bucket: pack every tenant's tree, ship
        one stacked full upload or one flat global delta, dispatch ONCE,
        verify each tenant's integrity digest, and return
        ``(rows, failed)`` — each SERVED tenant's packed decision row
        (digest stripped, host array) plus the tenants whose PACK phase
        raised (chaos seam / bad tree), mapped to their exception.

        ``items`` is the cycle's (tenant, tree) list in serving order; it
        must be a subset of ``bucket.members`` (with ``fleet_slots`` the
        fairness pass serves a rotating subset). A tenant that fails its
        own pack is EXCLUDED from this cycle's batch — every other tenant
        still dispatches together, which is the isolation contract: one
        tenant's fault never costs its bucket-mates their cycle. The
        caller serves the failed tenants through its per-tenant fallback
        ladder. On a digest trip the whole bucket recovers in place —
        full re-stack from the SOURCE trees + recompute, decision-neutral
        for every tenant (the flat kernel's recovery argument, per row).
        On a failed dispatch the bucket resets cold and the error
        propagates to the caller's degradation ladder.
        """
        assert set(n for n, _t in items) <= set(bucket.members), \
            "run_bucket items must be bucket members"
        # kernel-build normalization always forces scan (sharding=False):
        # the batched entry vmaps the cycle over the tenant axis, which
        # composes with lax control flow but not with a pallas_call —
        # sharded tenants only split bucket KEYS on use_pallas (place()),
        # the batched program itself stays pure-XLA
        cfg_n = normalize_config(cfg)
        if bucket.kernel is not None:
            spec, sizes = bucket.kernel.spec, bucket.kernel.sizes
        else:
            spec = fuse_spec(items[0][1])[1]
            sizes = group_sizes(spec)

        # ---- pack (per-tenant fault isolation) ---------------------------
        packed_bufs: Dict[str, tuple] = {}
        failed: Dict[str, BaseException] = {}
        good: List[Tuple[str, object]] = []
        with _spans.span("fleet.pack"):
            for name, tree in items:
                res = bucket.members[name]
                try:
                    # per-tenant chaos seam: resident corruption / targeted
                    # dispatch loss fire here, before this tenant's diff
                    seam("fleet.tenant", pool=self, bucket=bucket,
                         tenant=name, resident=res)
                    bufs = fuse_into(tree, spec, sizes, out=res.scratch)
                except Exception as e:
                    failed[name] = e
                    continue
                res.scratch = None
                packed_bufs[name] = bufs
                good.append((name, tree))
        names = tuple(n for n, _t in good)
        if not names:
            return {}, failed

        width = pow2_bucket(len(names), 1)
        if bucket.kernel is None or bucket.kernel.width != width:
            bucket.kernel = FleetDeltaKernel(
                cycle_fn_builder(cfg_n), good[0][1], width,
                entry=_entry_name(bucket.key, width),
                integrity=self.integrity)
            bucket.stacked_names = ()
            bucket.epoch += 1
        kernel = bucket.kernel
        _invalidate(bucket.retiring)
        bucket.retiring = ()

        # baseline[name]: the host values this tenant's device row holds
        # BEFORE the in-graph scatter — the delta ships fresh-vs-baseline.
        # None = the row stacks directly from the fresh pack (no delta).
        structural = (force_full or bucket.device is None
                      or bucket.stacked_names != names
                      or any(bucket.members[n].mirror is None
                             for n in names))
        if structural:
            baseline = {}
            for name in names:
                res = bucket.members[name]
                wm = None if force_full else res.warm_mirror
                res.warm_mirror = None
                # a digest-verified warm mirror (fleet checkpoint restore)
                # becomes this tenant's row; its first cycle diffs fresh
                # truth against it — the single-tenant adopt_mirror rule,
                # per row
                baseline[name] = wm
        else:
            baseline = {n: bucket.members[n].mirror for n in names}

        def _diff(baseline):
            deltas, total = [], 0
            for k in range(len(_GROUPS)):
                idx_parts, val_parts = [], []
                for r, name in enumerate(names):
                    base = baseline[name]
                    if base is None:
                        continue
                    new = packed_bufs[name][k]
                    li = np.flatnonzero(new != base[k]).astype(np.int32)
                    if li.size:
                        idx_parts.append(li + np.int32(r * sizes[k]))
                        val_parts.append(new[li])
                        total += int(li.size)
                if idx_parts:
                    deltas.append((np.concatenate(idx_parts),
                                   np.concatenate(val_parts)))
                else:
                    deltas.append((np.zeros(0, np.int32),
                                   np.zeros(0, _TARGETS[_GROUPS[k]])))
            return deltas, total

        with _spans.span("fleet.diff"):
            deltas, total = _diff(baseline)
        if not structural and 2 * total >= len(names) * sum(sizes):
            # a delta this large ships more bytes than a restack would:
            # take the full path (decisions identical either way)
            structural = True
            baseline = {n: None for n in names}
            deltas, total = _diff(baseline)

        upload = 0
        if structural:
            with _spans.span("fleet.upload"):
                stacked = []
                for k in range(len(_GROUPS)):
                    rows = [(baseline[n][k] if baseline[n] is not None
                             else packed_bufs[n][k]) for n in names]
                    # pad rows replicate row 0: their outputs are computed
                    # and discarded; pow2 padding bounds retraces
                    rows += [rows[0]] * (kernel.width - len(names))
                    stacked.append(np.ascontiguousarray(np.stack(rows)))
                _invalidate(bucket.device or ())
                dev = tuple(jax.device_put(s) for s in stacked)
            upload += int(sum(s.nbytes for s in stacked))
        else:
            dev = bucket.device
        args = []
        for k, (idx, vals) in enumerate(deltas):
            pidx, pvals = _pad_delta(idx, vals, delta_bucket(idx.size))
            args += [pidx, pvals]
            upload += int(pidx.nbytes + pvals.nbytes)

        # ---- one dispatch for the whole bucket ---------------------------
        seam("fleet.dispatch", pool=self, bucket=bucket, tenants=names)
        try:
            with _spans.span("fleet.dispatch", cat="dispatch"):
                fnew, inew, bnew, packed_dev = kernel._fn(*dev, *args)
            with _spans.span("fleet.readback", cat="wait"):
                packed = np.asarray(packed_dev)
        except Exception:
            self._reset_bucket(bucket)
            raise
        bucket.retiring = dev
        bucket.device = (fnew, inew, bnew)
        bucket.stacked_names = names

        # ---- per-tenant digest verify + accounting -----------------------
        per_tenant_upload = max(1, len(names))
        trip = None
        out: Dict[str, np.ndarray] = {}
        for r, name in enumerate(names):
            res = bucket.members[name]
            row = packed[r]
            if kernel.digest_words:
                dev_digest = np.ascontiguousarray(
                    row[-kernel.digest_words:]).view(np.uint32)
                row = row[:-kernel.digest_words]
                if not np.array_equal(dev_digest,
                                      host_digest(packed_bufs[name])):
                    trip = name
            out[name] = row
            # ping-pong: the old mirror becomes next cycle's scratch
            res.scratch, res.mirror = res.mirror, packed_bufs[name]
            res.last_kind = ("delta" if baseline.get(name) is not None
                             else "full")
            res.full_upload_bytes = int(sum(
                b.nbytes for b in packed_bufs[name]))
            res.last_upload_bytes = upload // per_tenant_upload
            if res.last_kind == "full":
                res.full_cycles += 1
            else:
                res.delta_cycles += 1
        if trip is not None:
            if force_full:
                # recovery itself tripped: residency is unrecoverable in
                # place; reset and let the caller's ladder take over
                self._reset_bucket(bucket)
                raise RuntimeError(
                    f"fleet integrity digest failed for tenant {trip!r} "
                    f"after full re-stack")
            METRICS.inc("resident_digest_mismatch_total")
            METRICS.inc("cycle_recoveries_total",
                        labels={"reason": "digest", "mode": "fleet_refuse"})
            _spans.log_event("digest_trip", source="fleet", tenant=trip)
            with _spans.span("fleet.recover", cat="recovery"):
                # full re-stack from SOURCE truth + recompute: heals both
                # divergence directions and is decision-neutral for every
                # tenant (clean rows recompute to identical decisions)
                for name in names:
                    bucket.members[name].mirror = None
                rows, failed2 = self.run_bucket(
                    bucket, cycle_fn_builder, cfg, good, force_full=True)
                failed.update(failed2)
                return rows, failed
        return out, failed

    def _reset_bucket(self, bucket: _Bucket) -> None:
        """After a failed dispatch the stacked residency is indeterminate
        (donation may or may not have consumed it): drop everything so the
        next run pays one clean restack."""
        _invalidate(bucket.retiring)
        _invalidate(bucket.device or ())
        bucket.retiring = ()
        bucket.device = None
        bucket.stacked_names = ()
        for res in bucket.members.values():
            res.mirror = None
            res.scratch = None
