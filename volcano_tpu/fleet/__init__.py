"""Multi-tenant fleet runtime: one compiled cycle serving many clusters.

- :mod:`.pool` — TenantPool / FleetDeltaKernel: per-tenant resident
  state stacked along a vmapped tenant axis, pow2 shape buckets, one
  dispatch per bucket;
- :mod:`.fairness` — cross-tenant cycle-slot fairness (the proportion
  plugin's water-fill lifted one level up);
- :mod:`.scheduler` — FleetScheduler: N full scheduling loops sharing
  the batched device dispatch, with per-tenant fault isolation,
  checkpoints, and observability;
- ``python -m volcano_tpu.fleet --smoke`` — the tier-1 equivalence
  smoke: a batched fleet's per-tenant decision stream must be
  bit-identical to N independent single-tenant schedulers.

See docs/architecture.md, "Fleet serving".
"""

from .fairness import pick_served, record_served, tenant_deserved  # noqa: F401
from .pool import (FleetDeltaKernel, TenantPool, bucket_key,  # noqa: F401
                   normalize_config)
from .scheduler import FleetScheduler, Tenant  # noqa: F401
