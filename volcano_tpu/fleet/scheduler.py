"""FleetScheduler: many clusters' scheduling loops through one batched
dispatch.

Each admitted tenant is a full single-cluster control loop — its own
FakeCluster source, conf, persistent Session (incremental reopen, exactly
runtime/scheduler.Scheduler's steady state), ResyncQueue, flight recorder,
and degradation ladder. What the fleet shares is the DEVICE: every cycle
the tenants' derived allocate inputs route to shape buckets
(fleet/pool.TenantPool) and each bucket dispatches ONCE for all its
members — B same-bucket tenants cost one dispatch instead of B.

Isolation contract (chaos-tested in tests/test_fleet.py):

- decisions: each tenant's packed row comes out of a vmapped cycle that
  cannot mix rows by construction (graphcheck family ``fleet``), is
  digest-verified against that tenant's own host mirror, and applies
  through that tenant's own Session — bit-identical to N independent
  Schedulers;
- faults: a tenant whose pack/dispatch faults is served through the
  per-tenant degradation ladder (sync retry -> CPU oracle, the
  runtime/scheduler ladder) while its bucket-mates' batched cycle
  proceeds untouched;
- structure: admission, eviction, and bucket migration bump ONLY the
  touched bucket's structural epoch — other buckets keep their compiled
  kernels and stacked residents (the no-cross-retrace claim, proven by
  the per-bucket jit trace counters);
- state: checkpoints are one PR 10 envelope PER TENANT
  (``tenant-<name>.vckp``); a corrupt file cold-fuses only its owner.

With conf ``fleet_slots`` set, the cross-tenant fairness pass
(fleet/fairness — the proportion plugin's water-fill lifted one level up)
picks which tenants each cycle serves; unset, every tenant is served
every cycle and the fleet is a pure batching transparency layer.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..framework.conf import SchedulerConfiguration, parse_conf
from ..framework.session import Session
from ..metrics import METRICS
from ..ops.allocate_scan import make_allocate_cycle
from ..runtime.scheduler import ResyncQueue
from ..telemetry import FlightRecorder, spans
from . import fairness
from .pool import TenantPool, _entry_name


class Tenant:
    """One admitted cluster's loop state: everything
    runtime/scheduler.Scheduler keeps per instance, minus the parts the
    fleet shares (the pool's device residency and the serving loop)."""

    def __init__(self, name: str, cluster,
                 conf: Optional[SchedulerConfiguration] = None,
                 weight: float = 1.0):
        self.name = name
        self.cluster = cluster
        self.conf = conf or parse_conf()
        self.weight = float(weight)
        self.session: Optional[Session] = None
        self.cycles = 0
        self.full_packs = 0
        self.incremental_cycles = 0
        self.incremental = hasattr(cluster, "live_view")
        self.resync = ResyncQueue()
        self.flight = FlightRecorder(
            capacity=int(os.environ.get("VOLCANO_FLIGHT_CYCLES", 64)))
        self._plugin_state: Dict[str, object] = {}
        # per-tenant degradation ladder (the runtime/scheduler ladder,
        # one rung counter per tenant): 0 = batched fleet path, 1 = a
        # fault was recovered synchronously, 2 = CPU oracle
        self.degradation_level = 0
        self.fault_cooldown = int(os.environ.get("VOLCANO_FAULT_COOLDOWN",
                                                 4))
        self._degrade_until = 0
        self._cycle_faults: List[dict] = []
        #: digest-verified mirrors from a per-tenant checkpoint restore,
        #: keyed by frozen bucket key; consumed at the next placement
        self.warm_mirrors: Dict[tuple, tuple] = {}
        self._last_dirty = (0, 0)


class FleetScheduler:
    """The fleet serving loop over a :class:`TenantPool`."""

    def __init__(self, conf: Optional[SchedulerConfiguration] = None,
                 integrity: bool = True):
        #: fleet-level conf: ``fleet_slots`` / ``fleet_checkpoint_dir``
        #: live here; each tenant still schedules under its OWN conf
        self.conf = conf or parse_conf()
        self.tenants: Dict[str, Tenant] = {}
        self.pool = TenantPool(integrity=integrity)
        self.cycles = 0
        #: cumulative cycles served per tenant — the fairness deficit
        #: counters (fleet/fairness.record_served)
        self.served: Dict[str, float] = {}

    # ------------------------------------------------- admission / eviction
    def admit(self, name: str, cluster,
              conf: Optional[SchedulerConfiguration] = None,
              weight: float = 1.0) -> Tenant:
        """Admit a tenant at runtime. Its bucket (joined lazily at its
        first served cycle) restacks; no other bucket is touched."""
        if name in self.tenants:
            raise ValueError(f"tenant {name!r} already admitted")
        t = Tenant(name, cluster, conf=conf, weight=weight)
        self.tenants[name] = t
        METRICS.inc("fleet_admissions_total", labels={"event": "admit"})
        METRICS.set_gauge("fleet_tenants", None, len(self.tenants))
        spans.log_event("fleet_admission", event="admit", tenant=name,
                        weight=weight, tenants=len(self.tenants))
        return t

    def evict(self, name: str) -> None:
        """Evict a tenant: its residency leaves its bucket (which
        restacks); every other bucket's kernel and residents survive."""
        t = self.tenants.pop(name, None)
        if t is None:
            return
        self.pool.evict(name)
        self.served.pop(name, None)
        METRICS.inc("fleet_admissions_total", labels={"event": "evict"})
        METRICS.set_gauge("fleet_tenants", None, len(self.tenants))
        spans.log_event("fleet_admission", event="evict", tenant=name,
                        tenants=len(self.tenants))

    # --------------------------------------------------- per-tenant session
    def _persistent_plugins(self, t: Tenant) -> Dict[str, object]:
        from ..plugins.reservation import ReservationPlugin
        from ..plugins.tdm import TDMPlugin
        overrides = {}
        for name, cls in (("reservation", ReservationPlugin),
                          ("tdm", TDMPlugin)):
            if t.conf.plugin_option(name) is not None:
                if name not in t._plugin_state:
                    t._plugin_state[name] = cls(t.conf.plugin_option(name))
                overrides[name] = t._plugin_state[name]
        return overrides

    def _open_session(self, t: Tenant, now: Optional[float]) -> Session:
        """Open this tenant's cycle session — Scheduler._open_session per
        tenant: one persistent Session re-opened incrementally from the
        cluster's dirty marks; full pack only on the first cycle, on
        structural changes, or on a documented refresh fallback."""
        overrides = self._persistent_plugins(t)
        if not t.incremental:
            return Session(t.cluster.snapshot(), t.conf, now=now,
                           plugin_overrides=overrides)
        dj, dn, structural = t.cluster.drain_dirty()
        t._last_dirty = (len(dj), len(dn))
        ssn = t.session
        if ssn is None or structural:
            ssn = Session(t.cluster.live_view(), t.conf, now=now,
                          plugin_overrides=overrides)
            t.session = ssn
            t.full_packs += 1
            return ssn
        for uid in dj:
            ssn._dirty_jobs.add(uid)
        for name in dn:
            ssn._dirty_nodes.add(name)
        if ssn.reopen(now=now, conf=t.conf, plugin_overrides=overrides):
            t.incremental_cycles += 1
        else:
            t.full_packs += 1
        return ssn

    # ------------------------------------------------------- fault handling
    def _note_fault(self, t: Tenant, stage: str, exc: BaseException) -> None:
        METRICS.inc("cycle_faults_total", labels={"stage": stage})
        t._cycle_faults.append(
            dict(stage=stage, error=f"{type(exc).__name__}: {exc}"))

    def _degrade(self, t: Tenant, level: int) -> None:
        prev = t.degradation_level
        t.degradation_level = max(t.degradation_level, level)
        if t.degradation_level != prev:
            spans.log_event("degradation", tenant=t.name, level_from=prev,
                            level_to=t.degradation_level, cycle=t.cycles)
        t._degrade_until = t.cycles + t.fault_cooldown
        METRICS.set_gauge("fleet_tenant_degradation", {"tenant": t.name},
                          t.degradation_level)

    def _allocate_fallback(self, t: Tenant, ssn: Session,
                           exc: BaseException):
        """This tenant's batched serving faulted (pack seam, bucket
        dispatch, or digest-unrecoverable): walk ITS ladder alone — the
        single-tenant compiled path, then the CPU oracle. Decisions stay
        bit-identical on every rung, so a faulted tenant degrades in
        latency only; its bucket-mates never see any of this."""
        self._note_fault(t, "fleet_allocate", exc)
        t0 = time.time()
        with spans.span("cycle.recovery", cat="recovery"):
            try:
                result = ssn.run_allocate()
                mode = "sync"
                self._degrade(t, 1)
            except Exception as e:
                self._note_fault(t, "sync_retry", e)
                result = ssn.run_allocate_oracle()
                mode = "cpu_oracle"
                self._degrade(t, 2)
        METRICS.inc("cycle_recoveries_total",
                    labels={"reason": "dispatch", "mode": mode})
        spans.log_event("recovery", stage="fleet_allocate", mode=mode,
                        tenant=t.name, cycle=t.cycles,
                        recovery_ms=round((time.time() - t0) * 1000, 3))
        return result

    # ------------------------------------------------------------ the cycle
    def run_once(self, now: Optional[float] = None) -> Dict[str, Session]:
        """One fleet cycle: fairness pick -> per-tenant open + pre-allocate
        actions -> bucket-grouped batched allocate (ONE dispatch per
        bucket) -> per-tenant apply + flush. Returns {tenant: Session} for
        the tenants served this cycle."""
        t_open = time.time()
        wall = now if now is not None else t_open
        from ..chaos.inject import seam
        seam("fleet.cycle", cycle=self.cycles, fleet=self)
        slots = getattr(self.conf, "fleet_slots", None)
        weights = {n: t.weight for n, t in self.tenants.items()}
        picked = fairness.pick_served(weights, self.served, slots)

        # ---- open + pre-allocate actions, group by bucket ---------------
        from ..actions import get_action
        entries = []            # dicts: tenant, ssn, cfg, tree, T, J, t0
        by_bucket: Dict[tuple, list] = {}
        for name in picked:
            t = self.tenants[name]
            t0 = time.time()
            if t.degradation_level and t.cycles >= t._degrade_until:
                spans.log_event("degradation", tenant=name,
                                level_from=t.degradation_level, level_to=0,
                                cycle=t.cycles)
                t.degradation_level = 0
                METRICS.set_gauge("fleet_tenant_degradation",
                                  {"tenant": name}, 0)
            if len(t.resync):
                rs = t.resync.process(t.cluster, wall)
                METRICS.inc("resync_retried", rs["retried"])
                METRICS.inc("resync_succeeded", rs["succeeded"])
                METRICS.inc("resync_dropped", rs["dropped"])
                if rs["dead_lettered"]:
                    METRICS.inc("resync_dead_letter_total",
                                rs["dead_lettered"])
            with spans.span("cycle.open", tenant=name):
                ssn = self._open_session(t, now)
            actions = list(t.conf.actions)
            batched = bool(actions) and actions[-1] == "allocate"
            entry = dict(tenant=t, ssn=ssn, t0=t0, batched=batched)
            try:
                for aname in (actions[:-1] if batched else actions):
                    ta = time.time()
                    with spans.span(f"action.{aname}", tenant=name):
                        try:
                            get_action(aname).execute(ssn)
                        except Exception as e:
                            if aname != "allocate":
                                raise
                            # non-batched tenant's compiled allocate
                            # failed mid-action: its own ladder
                            self._allocate_fallback(t, ssn, e)
                    METRICS.observe_action(aname, time.time() - ta)
            except Exception as e:
                # a non-allocate action raised: this tenant's cycle is
                # unservable — retire it without decisions; the fleet
                # keeps serving everyone else
                self._note_fault(t, "action", e)
                METRICS.inc("cycle_dropped_total")
                ssn.stats["cycle_dropped"] = 1.0
                self._finish_tenant(t, ssn, time.time() - t0, wall)
                continue
            if batched:
                with spans.span("session.extras", tenant=name):
                    cfg, extras = ssn.allocate_inputs()
                tree = (ssn.snap, extras)
                entry.update(
                    cfg=cfg, tree=tree,
                    T=int(np.asarray(ssn.snap.tasks.status).shape[0]),
                    J=int(np.asarray(ssn.snap.jobs.valid).shape[0]))
                bucket = self.pool.place(
                    name, cfg, tree,
                    sharding=bool(getattr(t.conf, "sharding", False)))
                if t.warm_mirrors:
                    from ..runtime.checkpoint import _freeze_key
                    mir = t.warm_mirrors.pop(_freeze_key(bucket.key), None)
                    if mir is not None:
                        bucket.members[name].warm_mirror = mir
                by_bucket.setdefault(self.pool.placement[name],
                                     []).append(entry)
            entries.append(entry)

        # ---- one dispatch per bucket ------------------------------------
        for key, group in by_bucket.items():
            bucket = self.pool.buckets[key]
            items = [(e["tenant"].name, e["tree"]) for e in group]
            try:
                rows, failed = self.pool.run_bucket(
                    bucket, make_allocate_cycle, group[0]["cfg"], items)
            except Exception as e:
                # the whole-bucket dispatch failed (backend loss): every
                # member walks its own ladder; buckets are independent,
                # so other buckets' dispatches proceed normally
                rows, failed = {}, {e2["tenant"].name: e for e2 in group}
            for e in group:
                t, ssn, name = e["tenant"], e["ssn"], e["tenant"].name
                row = rows.get(name)
                if row is not None:
                    try:
                        ta = time.time()
                        with spans.span("fleet.apply", tenant=name):
                            result = ssn.apply_packed(
                                np.ascontiguousarray(row), e["T"], e["J"])
                        spans.record_tenant_phase(
                            name, "apply", (time.time() - ta) * 1000.0)
                    except Exception as exc:
                        result = self._allocate_fallback(t, ssn, exc)
                else:
                    result = self._allocate_fallback(
                        t, ssn, failed.get(name,
                                           RuntimeError("not served")))
                ssn.stats["allocated_binds"] = len(ssn.binds)
                ssn.stats["jobs_ready"] = int(
                    np.asarray(result.job_ready).sum())
                ssn.stats["jobs_pipelined"] = int(
                    np.asarray(result.job_pipelined).sum())

        # ---- per-tenant flush (cluster writes never cross tenants) ------
        out = {}
        for e in entries:
            t, ssn = e["tenant"], e["ssn"]
            if ssn.stats.get("cycle_dropped"):
                continue        # already retired above
            self._finish_tenant(t, ssn, time.time() - e["t0"], wall)
            out[t.name] = ssn
        fairness.record_served(self.served, [e["tenant"].name
                                             for e in entries])
        self.cycles += 1
        ckpt_dir = getattr(self.conf, "fleet_checkpoint_dir", None)
        if ckpt_dir:
            self.checkpoint(ckpt_dir, now=wall)
        return out

    def _finish_tenant(self, t: Tenant, ssn: Session, host_s: float,
                       wall: float) -> None:
        """Scheduler._finish_cycle per tenant: close, write back phases,
        flush intents against THIS tenant's cluster (failures retry on
        this tenant's ResyncQueue), metrics, and a flight record carrying
        the tenant label + this tenant's share of the batched upload."""
        with spans.span("cycle.finish", tenant=t.name):
            ssn.close()
            t.cluster.update_podgroup_phases(ssn.phase_updates)
            for intent in ssn.evictions:
                if not t.cluster.evict(intent):
                    METRICS.inc("resync_tasks")
                    t.resync.add(intent, "evict", wall)
            for intent in ssn.binds:
                if not t.cluster.bind(intent):
                    METRICS.inc("resync_tasks")
                    t.cluster.hold_binding(intent)
                    t.resync.add(intent, "bind", wall)
        METRICS.observe_cycle(host_s)
        spans.record_tenant_phase(t.name, "tenant_cycle", host_s * 1000.0)
        METRICS.inc("schedule_attempts")
        result = ("error" if ssn.bind_errors
                  else "scheduled" if (ssn.binds or ssn.pipelined)
                  else "unschedulable")
        METRICS.inc("schedule_attempts_total", labels={"result": result})
        METRICS.inc("fleet_cycles_total", labels={"tenant": t.name})
        from ..telemetry import publish_gauges
        publish_gauges(METRICS)
        spans.publish_gauges(METRICS)
        t.cycles += 1
        bucket = self.pool.bucket_of(t.name)
        res = bucket.members.get(t.name) if bucket is not None else None
        stats = ssn.stats
        faults, t._cycle_faults = t._cycle_faults, []
        t.flight.record(
            now=wall, cycle=t.cycles, tenant=t.name,
            cycle_ms=round(host_s * 1000, 3),
            binds=len(ssn.binds), evictions=len(ssn.evictions),
            pipelined=len(ssn.pipelined), bind_errors=len(ssn.bind_errors),
            resync_pending=len(t.resync), result=result,
            faults=faults or None, degradation=t.degradation_level,
            resync_dead_letter=len(t.resync.dead),
            fleet_bucket=(_entry_name(bucket.key, bucket.width)
                          if bucket is not None and bucket.kernel else None),
            fleet_epoch=bucket.epoch if bucket is not None else None,
            cycle_kind=res.last_kind if res is not None else None,
            upload_bytes=(res.last_upload_bytes if res is not None
                          else stats.get("upload_bytes")),
            upload_bytes_full=(res.full_upload_bytes if res is not None
                               else stats.get("upload_bytes_full")),
            dirty_jobs=t._last_dirty[0], dirty_nodes=t._last_dirty[1],
            stats={k: round(float(v), 3) for k, v in stats.items()},
            telemetry=ssn.last_telemetry or None,
            spans=spans.drain_cycle_summary())

    def run(self, cycles: int = 1,
            now: Optional[float] = None) -> List[Dict[str, Session]]:
        out = []
        for i in range(cycles):
            out.append(self.run_once(
                now=(now + i) if now is not None else None))
        return out

    # -------------------------------------------------------- observability
    def fleet_snapshot(self) -> dict:
        """The dashboard's /api/fleet payload: every tenant with its
        bucket, serving counters, and degradation rung."""
        tenants = []
        for name in sorted(self.tenants):
            t = self.tenants[name]
            bucket = self.pool.bucket_of(name)
            res = bucket.members.get(name) if bucket is not None else None
            tenants.append(dict(
                tenant=name, weight=t.weight, cycles=t.cycles,
                served=self.served.get(name, 0.0),
                degradation=t.degradation_level,
                bucket=(_entry_name(bucket.key, bucket.width)
                        if bucket is not None and bucket.kernel else None),
                bucket_width=bucket.width if bucket is not None else 0,
                bucket_epoch=bucket.epoch if bucket is not None else None,
                cycle_kind=res.last_kind if res is not None else None,
                full_cycles=res.full_cycles if res is not None else 0,
                delta_cycles=res.delta_cycles if res is not None else 0,
                full_packs=t.full_packs,
                incremental_cycles=t.incremental_cycles,
                resync_pending=len(t.resync),
                resync_dead_letter=len(t.resync.dead)))
        return dict(cycles=self.cycles,
                    slots=getattr(self.conf, "fleet_slots", None),
                    buckets=len(self.pool.buckets),
                    tenants=tenants)

    # ------------------------------------------- per-tenant checkpointing
    def checkpoint(self, directory: str,
                   now: Optional[float] = None) -> Dict[str, dict]:
        """One PR 10 envelope per tenant under ``directory``
        (``tenant-<name>.vckp``): loop counters, retry state, and the
        tenant's digest-stamped resident mirror. Independent files are
        the isolation property: damage to one tenant's file can only
        cold-fuse that tenant."""
        from ..runtime import checkpoint as ckpt
        os.makedirs(directory, exist_ok=True)
        out = {}
        for name in sorted(self.tenants):
            t = self.tenants[name]
            bucket = self.pool.bucket_of(name)
            res = bucket.members.get(name) if bucket is not None else None
            mirrors = []
            if res is not None and res.mirror is not None:
                from ..ops.fused_io import host_digest
                mirror = tuple(np.array(b, copy=True) for b in res.mirror)
                mirrors = [{"key": bucket.key, "mirror": mirror,
                            "digest": [int(x) for x in host_digest(mirror)]}]
            state = dict(
                name=name, weight=t.weight, cycles=t.cycles,
                full_packs=t.full_packs,
                incremental_cycles=t.incremental_cycles,
                degradation_level=t.degradation_level,
                degrade_until=t._degrade_until,
                served=self.served.get(name, 0.0),
                conf_fingerprint=ckpt.conf_fingerprint(t.conf),
                resync_entries=[dict(e) for e in t.resync.entries],
                resync_dead=[dict(e) for e in t.resync.dead],
                metrics=ckpt.metrics_snapshot())
            out[name] = ckpt.write_checkpoint(
                ckpt.tenant_checkpoint_path(directory, name),
                "fleet-tenant", state, mirrors=mirrors)
        return out

    def restore(self, directory: str,
                now: Optional[float] = None) -> Dict[str, str]:
        """Restore every admitted tenant from its own envelope. Outcomes
        per tenant (``checkpoint_restore_total{outcome=...}``): a missing
        file is a cold start, a damaged or conf-mismatched file falls
        back to cold — and ONLY that tenant does; a corrupt envelope
        never stalls the fleet. Returns {tenant: outcome}."""
        from ..runtime import checkpoint as ckpt
        wall = now if now is not None else time.time()
        out = {}
        for name in sorted(self.tenants):
            t = self.tenants[name]
            t0 = time.time()
            env, reason = ckpt.load_checkpoint(
                ckpt.tenant_checkpoint_path(directory, name),
                "fleet-tenant")
            if env is None:
                outcome = "cold" if reason == "missing" else "fallback"
                ckpt.record_restore(outcome, reason, f"fleet:{name}",
                                    (time.time() - t0) * 1000)
                out[name] = outcome
                continue
            state = env["state"]
            if state.get("conf_fingerprint") != \
                    ckpt.conf_fingerprint(t.conf):
                ckpt.record_restore("fallback", "conf_mismatch",
                                    f"fleet:{name}",
                                    (time.time() - t0) * 1000)
                out[name] = "fallback"
                continue
            t.cycles = int(state["cycles"])
            t.full_packs = int(state["full_packs"])
            t.incremental_cycles = int(state["incremental_cycles"])
            t.degradation_level = int(state["degradation_level"])
            t._degrade_until = int(state["degrade_until"])
            self.served[name] = float(state.get("served", 0.0))
            t.resync.entries = [dict(e) for e in state["resync_entries"]]
            t.resync.dead = [dict(e) for e in state["resync_dead"]]
            ckpt.merge_metrics(state.get("metrics"))
            t.session = None
            t.warm_mirrors = ckpt.verify_mirrors(env.get("mirrors"))
            t.resync.redrive(wall)
            ckpt.record_restore("restored", "ok", f"fleet:{name}",
                                (time.time() - t0) * 1000)
            out[name] = "restored"
        return out
