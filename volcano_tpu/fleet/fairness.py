"""Cross-tenant cycle fairness: the proportion plugin's qshare machinery
lifted one level up.

Inside one cluster the proportion plugin water-fills cluster capacity over
queues by weight (ops/fairshare.proportion_deserved). The fleet has the
same shape one level up: the contended resource is CYCLE SLOTS (how many
tenants the batched runtime serves per fleet cycle, conf ``fleet_slots``),
the actors are tenants, and the weights are admission weights. This
module is the single-resource host-side form of the same fixed point —
repeatedly hand each unmet tenant ``remaining * w / sum(unmet weights)``,
clamp by request, recycle the clamped-off remainder — plus the
deficit-counter serving order that turns long-run deserved shares into a
deterministic per-cycle pick.

With ``fleet_slots`` unset (the default) every tenant is served every
cycle and this module is a no-op passthrough — which is what keeps the
fleet's decision stream bit-identical to N independent schedulers; the
fairness pass only bites under load.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

_EPS = 1e-9


def tenant_deserved(weights: Dict[str, float], slots: float,
                    requests: Dict[str, float] = None,
                    max_iters: int = 16) -> Dict[str, float]:
    """Each tenant's deserved cycle-slot share by weighted water-filling —
    the proportion fixed point (proportion.go:140-197 / ops/fairshare) in
    its 1-resource host form. ``requests`` caps a tenant's useful share
    (a tenant can't use more than one slot per cycle: default 1.0)."""
    names = sorted(weights)
    if not names:
        return {}
    req = {n: (requests or {}).get(n, 1.0) for n in names}
    deserved = {n: 0.0 for n in names}
    meet = {n: weights[n] <= 0 for n in names}
    remaining = float(slots)
    for _ in range(max_iters):
        unmet_w = sum(weights[n] for n in names if not meet[n])
        if unmet_w <= _EPS or remaining <= _EPS:
            break
        changed = False
        for n in names:
            if meet[n]:
                continue
            proposed = deserved[n] + remaining * weights[n] / unmet_w
            new = min(proposed, req[n])
            if new > deserved[n] + _EPS:
                changed = True
            if new >= req[n] - _EPS:
                meet[n] = True
            deserved[n] = new
        handed = sum(deserved.values())
        remaining = float(slots) - handed
        if not changed:
            break
    return deserved


def pick_served(weights: Dict[str, float], served: Dict[str, float],
                slots: int) -> List[str]:
    """The tenants to serve this fleet cycle: the ``slots`` highest
    deficits, where a tenant's deficit is its deserved share of all slots
    handed out so far minus what it actually got. Deterministic: ties
    break by tenant name, so two runs of the same admission/weight history
    serve identical sequences (the fleet smoke pins this)."""
    names = sorted(weights)
    if slots is None or slots >= len(names):
        return names
    slots = max(0, int(slots))
    total_handed = sum(served.get(n, 0.0) for n in names) + slots
    shares = tenant_deserved(weights, float(total_handed))
    ranked = sorted(
        names,
        key=lambda n: (-(shares.get(n, 0.0) - served.get(n, 0.0)), n))
    return sorted(ranked[:slots])


def record_served(served: Dict[str, float], picked: Sequence[str]) -> None:
    """Advance the deficit counters for a cycle's served set."""
    for n in picked:
        served[n] = served.get(n, 0.0) + 1.0
