"""Fleet equivalence smoke: ``python -m volcano_tpu.fleet --smoke``.

The claim under test is the fleet's transparency contract: B tenants
served through ONE batched vmapped dispatch per shape bucket make
bit-identical decisions to B independent single-tenant schedulers run
over identically-seeded clusters — across multi-cycle runs with churn
(gang completions + re-arrivals), a mid-run eviction, and a mid-run
admission. The per-(tenant, cycle) sha matrix must match entry for
entry, and the jit trace counters must show ONE trace per
(bucket, width) program — never one per tenant.

Exit 0 on equivalence, 1 with the failing matrix on stderr otherwise.
Wired into scripts/tier1.sh (skip: TIER1_SKIP_FLEET=1).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time


def _sha(digests) -> str:
    return hashlib.sha256(repr(digests).encode()).hexdigest()[:16]


def run_fleet_smoke(cycles: int = 6, verbose: bool = False) -> dict:
    from ..chaos.probe import _PROBE_CONF, _churn, _cycle_digest, _small_cluster
    from ..framework.conf import parse_conf
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler
    from ..telemetry.tracecount import counts
    from .scheduler import FleetScheduler

    # two shape buckets: a/b share one, c/d share the other
    specs = {
        "tenant-a": dict(n_nodes=6, n_jobs=8, tasks_per_job=3, weight=2.0),
        "tenant-b": dict(n_nodes=6, n_jobs=8, tasks_per_job=3, weight=1.0),
        "tenant-c": dict(n_nodes=5, n_jobs=6, tasks_per_job=2, weight=1.0),
        "tenant-d": dict(n_nodes=5, n_jobs=6, tasks_per_job=2, weight=1.0),
    }
    evict_at = {"tenant-b": cycles - 2}     # mid-run eviction
    admit_at = {"tenant-d": 2}              # mid-run admission
    bases = {n: _small_cluster(**{k: v for k, v in s.items()
                                  if k != "weight"})
             for n, s in specs.items()}

    # ---- batched fleet run ---------------------------------------------
    t0 = time.time()
    fleet = FleetScheduler(conf=parse_conf(_PROBE_CONF))
    fleet_clusters = {n: FakeCluster(bases[n].clone()) for n in specs}
    for n, s in specs.items():
        if admit_at.get(n, 0) == 0:
            fleet.admit(n, fleet_clusters[n], conf=parse_conf(_PROBE_CONF),
                        weight=s["weight"])
    fleet_digests = {n: [] for n in specs}
    for c in range(cycles):
        for n in specs:
            if admit_at.get(n, 0) == c and n not in fleet.tenants:
                fleet.admit(n, fleet_clusters[n],
                            conf=parse_conf(_PROBE_CONF),
                            weight=specs[n]["weight"])
            if evict_at.get(n) == c:
                fleet.evict(n)
        served = fleet.run_once(now=1000.0 + c)
        for n, ssn in served.items():
            fleet_digests[n].append(_cycle_digest(ssn))
        for n in fleet.tenants:
            _churn(fleet_clusters[n], c)
    fleet_s = time.time() - t0
    fleet_entries = {e: v["traces"] for e, v in counts().items()
                     if e.startswith("fleet_cycle/")}

    # ---- N independent single-tenant reference runs --------------------
    t0 = time.time()
    solo_digests = {n: [] for n in specs}
    for n, s in specs.items():
        cluster = FakeCluster(bases[n].clone())
        sched = Scheduler(cluster, conf=parse_conf(_PROBE_CONF))
        first = admit_at.get(n, 0)
        last = evict_at.get(n, cycles)
        for c in range(cycles):
            if c < first or c >= last:
                continue
            ssn = sched.run_once(now=1000.0 + c)
            solo_digests[n].append(_cycle_digest(ssn))
            _churn(cluster, c)
    solo_s = time.time() - t0

    # ---- the sha matrix -------------------------------------------------
    matrix, ok = {}, True
    for n in sorted(specs):
        f_sha, s_sha = _sha(fleet_digests[n]), _sha(solo_digests[n])
        match = (fleet_digests[n] == solo_digests[n])
        ok = ok and match
        matrix[n] = dict(fleet_sha=f_sha, solo_sha=s_sha, match=match,
                         cycles=len(fleet_digests[n]))
        if not match and verbose:
            for c, (a, b) in enumerate(zip(fleet_digests[n],
                                           solo_digests[n])):
                if a != b:
                    print(f"  {n} cycle {c}: fleet={a!r} solo={b!r}",
                          file=sys.stderr)
    # compile discipline: one program per (bucket, width) — never per
    # tenant — with the flat kernel's O(log) delta-bucket trace budget
    # per program (full-stack signature + a few pow2 delta signatures)
    trace_ok = (len(fleet_entries) > 0
                and all(v <= 3 for v in fleet_entries.values())
                and len(fleet_entries) <= 2 * len(specs))
    return dict(ok=bool(ok and trace_ok), decisions_ok=bool(ok),
                trace_ok=bool(trace_ok), cycles=cycles,
                tenants=len(specs), matrix=matrix,
                fleet_entries=fleet_entries,
                buckets=len(fleet.pool.buckets),
                fleet_s=round(fleet_s, 3), solo_s=round(solo_s, 3),
                snapshot=fleet.fleet_snapshot())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m volcano_tpu.fleet")
    ap.add_argument("--smoke", action="store_true",
                    help="run the fleet-vs-independent equivalence smoke")
    ap.add_argument("--cycles", type=int, default=6)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not args.smoke:
        ap.print_help()
        return 2
    report = run_fleet_smoke(cycles=args.cycles, verbose=args.verbose)
    print(json.dumps(report, indent=2, default=str))
    if not report["ok"]:
        print("FLEET SMOKE FAILED: "
              + ("decision divergence" if not report["decisions_ok"]
                 else "trace-count violation"), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
