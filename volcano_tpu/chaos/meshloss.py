"""Elastic-mesh degradation probe: permanent device loss, shrink, regrow.

The ISSUE 5 chaos probe proves the ladder survives TRANSIENT faults; this
probe proves the elastic-mesh rung (ISSUE 20) survives PERSISTENT ones.
On an 8-device CPU mesh it plants two ``device_loss`` faults that stay
dead: the first must quarantine its device within the strike budget and
shrink the serving mesh 8 -> 4, the second 4 -> 2; after the probation
interval the health registry must regrow 2 -> 4 -> 8 over the healed
devices — and the full decision sha over every cycle must be
bit-identical to the clean unshrunk run, on the scan AND the
pallas-interpret sharded cycle paths (the re-fuse-from-source-truth
argument: no decision ever depended on the mesh width). A separate
``device_flap`` leg readmits a device that dies every time a regrown mesh
includes it and asserts flap damping bounds the re-mesh churn (the
probation interval doubles per re-failure through the stateful Backoff).

Shared by the tier-1 smoke (``python -m volcano_tpu.chaos --smoke
--meshloss``) and bench.py's ``robustness`` block
(``remesh_ms_p50`` / ``post_shrink_steady_ms_p50`` feed the regression
guard).
"""

from __future__ import annotations

import contextlib
import hashlib
from typing import Dict, Optional

from .inject import FaultInjector, chaos
from .plan import Fault, FaultPlan
from .probe import _PROBE_CONF, _churn, _cycle_digest, _small_cluster

#: health-registry knobs the probe pins (explicit, so env vars can't move
#: the asserted shrink/regrow timeline): 2 strikes in 8 cycles
#: quarantines, 3-cycle probation, 6-cycle flap window
_STRIKES, _WINDOW, _PROBATION, _FLAP_WINDOW = 2, 8, 3, 6


def _p50(values):
    values = sorted(values)
    return values[len(values) // 2] if values else None


def _width_runs(widths):
    """Compress the per-cycle width sequence to its distinct runs —
    [8, 8, 4, 4, 2, 2, 4, 8] -> [8, 4, 2, 4, 8]."""
    runs = []
    for w in widths:
        if w is not None and (not runs or runs[-1] != w):
            runs.append(w)
    return runs


def run_meshloss_probe(seed: int = 7, cycles: int = 16,
                       use_pallas: Optional[str] = None,
                       devices: int = 8, flap: bool = False,
                       pipeline: bool = True) -> Dict[str, object]:
    """One leg: a clean run vs a planted persistent-loss (or flap) storm
    on the sharded scheduler; returns a JSON-ready report."""
    import jax

    from ..framework.conf import parse_conf
    from ..metrics import METRICS
    from ..parallel.health import HEALTH
    from ..runtime.driver import step_cycle
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler
    if len(jax.devices()) < devices:
        return {"error": f"needs {devices} devices, have "
                         f"{len(jax.devices())} (set XLA_FLAGS="
                         f"--xla_force_host_platform_device_count="
                         f"{devices})"}
    conf = parse_conf(f"sharding: true\nsharding_devices: {devices}\n"
                      + (f"use_pallas: {use_pallas}\n" if use_pallas else "")
                      + _PROBE_CONF)
    base = _small_cluster()

    def run(injector):
        HEALTH.configure(strikes=_STRIKES, window=_WINDOW,
                         probation=_PROBATION, flap_window=_FLAP_WINDOW)
        cluster = FakeCluster(base.clone())
        sched = Scheduler(cluster, conf=conf, pipeline=pipeline)
        digests = []
        ctx = chaos(injector) if injector is not None \
            else contextlib.nullcontext()
        with ctx:
            for c in range(cycles):
                rec = step_cycle(sched, now=1000.0 + c)
                digests.append(_cycle_digest(rec))
                _churn(cluster, c)
        sha = hashlib.sha256(repr(digests).encode()).hexdigest()[:16]
        return sha, sched

    try:
        clean_sha, _clean = run(None)
        if flap:
            # one device that re-dies on every readmission: param 6 picks
            # device id 6 on the full mesh, heal_after=2 revives it well
            # before each probation regrow readmits (and re-kills) it
            plan = FaultPlan.explicit(
                [Fault("device_flap", 2, 6)], cycles=cycles, seed=seed)
            injector = FaultInjector(plan, heal_after=2)
        else:
            # loss at cycle 2 kills device 6 of the 8-mesh (param % 8);
            # loss at cycle 4 kills device 3 of the then-serving 4-mesh
            # (param 7 % 4 -> index 3). heal_after=3 revives each before
            # the probation regrow re-serves on it.
            plan = FaultPlan.explicit(
                [Fault("device_loss", 2, 6), Fault("device_loss", 4, 7)],
                cycles=cycles, seed=seed)
            injector = FaultInjector(plan, heal_after=3)
        shrinks0 = METRICS.counter_total("mesh_shrink_total")
        regrows0 = METRICS.counter_value("mesh_regrow_total")
        fault_sha, sched = run(injector)
        interval_after = HEALTH.probation_interval
    finally:
        HEALTH.configure()       # restore env-default knobs, clean state

    flight = sched.flight.snapshots()
    widths = [e.get("mesh_devices") for e in flight]
    width_runs = _width_runs(widths)
    shrunk_at = next((i for i, w in enumerate(widths)
                      if w is not None and w < devices), None)
    # zero-resharding contract on the post-shrink steady path: once the
    # mesh shrank, every sharded cycle must still leave its residents in
    # the sharding they entered with
    post_copies = sum(int(e.get("resharding_copies") or 0)
                      for e in flight[shrunk_at:]) \
        if shrunk_at is not None else None
    remesh_ms = [e["stats"]["remesh_ms"] for e in flight
                 if "remesh_ms" in e.get("stats", {})]
    steady_shrunk = [e["cycle_ms"] for e in flight
                     if e.get("mesh_devices") is not None
                     and e["mesh_devices"] < devices
                     and not e.get("faults")
                     and "remesh_ms" not in e.get("stats", {})]
    shrinks = METRICS.counter_total("mesh_shrink_total") - shrinks0
    regrows = METRICS.counter_value("mesh_regrow_total") - regrows0
    return {
        "seed": seed,
        "cycles": cycles,
        "devices": devices,
        "use_pallas": use_pallas,
        "flap": flap,
        "fault_schedule_sha": plan.schedule_sha(),
        "fault_log": [list(f) for f in injector.fired],
        "decisions_sha": fault_sha,
        "clean_sha": clean_sha,
        "decisions_equal_clean": fault_sha == clean_sha,
        "width_sequence": width_runs,
        "widths_hit": sorted({w for w in widths if w is not None}),
        "ends_full_width": bool(widths and widths[-1] == devices),
        "mesh_shrinks": shrinks,
        "mesh_regrows": regrows,
        "remesh_events": shrinks + regrows,
        "post_shrink_resharding_copies": post_copies,
        "remesh_ms_p50": _p50(remesh_ms),
        "post_shrink_steady_ms_p50": _p50(steady_shrunk),
        "probation_interval_after": interval_after,
        "degradation_max": max((e.get("degradation", 0) or 0)
                               for e in flight) if flight else 0,
    }


def check_loss_leg(report: Dict[str, object], devices: int = 8) -> list:
    """The acceptance assertions for a loss leg, as failure strings."""
    failures = []
    if report.get("error"):
        return [str(report["error"])]
    if not report["decisions_equal_clean"]:
        failures.append(
            f"decisions diverged from clean run "
            f"({report['decisions_sha']} != {report['clean_sha']}, "
            f"use_pallas={report['use_pallas']})")
    want = [devices, devices // 2, devices // 4]
    if report["widths_hit"] != sorted(set(want)):
        failures.append(f"expected mesh widths {sorted(set(want))}, "
                        f"served on {report['widths_hit']}")
    runs = report["width_sequence"]
    if runs[:3] != want:
        failures.append(f"shrink sequence {runs} does not start "
                        f"{want[0]}->{want[1]}->{want[2]}")
    if not report["ends_full_width"]:
        failures.append(f"probation did not regrow to {devices} wide "
                        f"(width sequence {runs})")
    if report["mesh_shrinks"] != 2:
        failures.append(f"expected 2 quarantine shrinks, "
                        f"counted {report['mesh_shrinks']}")
    if report["mesh_regrows"] != 2:
        failures.append(f"expected 2 probation regrows, "
                        f"counted {report['mesh_regrows']}")
    if report["post_shrink_resharding_copies"] != 0:
        failures.append(
            f"post-shrink steady path took "
            f"{report['post_shrink_resharding_copies']} resharding copies "
            f"(must be 0)")
    return failures


def check_flap_leg(report: Dict[str, object],
                   max_remesh: int = 6) -> list:
    """Acceptance for the flap leg: decision-neutral AND damped."""
    failures = []
    if report.get("error"):
        return [str(report["error"])]
    if not report["decisions_equal_clean"]:
        failures.append(
            f"flap decisions diverged from clean run "
            f"({report['decisions_sha']} != {report['clean_sha']})")
    if report["remesh_events"] > max_remesh:
        failures.append(
            f"flap damping failed: {report['remesh_events']} re-mesh "
            f"events (shrinks+regrows) exceed the damped bound "
            f"{max_remesh}")
    if report["probation_interval_after"] <= _PROBATION:
        failures.append(
            f"probation interval never escalated under flapping "
            f"(still {report['probation_interval_after']})")
    return failures
