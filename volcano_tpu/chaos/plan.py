"""Deterministic, seeded fault plans for the cycle runtime.

The reference scheduler earns its HA claims from machinery that only runs
when things break: rate-limited retry queues, informer resyncs, leader
re-election. Our TPU-native loop has MORE volatile state (device-resident
buffers, a one-deep pipeline, a wire protocol) and the failure handling is
only trustworthy if every recovery path is exercised on purpose. A
:class:`FaultPlan` is a reproducible storm: given a seed it derives the
exact same schedule of faults (kind, cycle, parameter) every time, so a
chaos run is as replayable as a unit test — two runs with the same seed
must produce the same fault log AND the same post-recovery decision sha
(tests/test_chaos.py).

Fault kinds (the seams they fire at live in :mod:`.inject`):

- ``socket_drop``      — sidecar client socket dies after the request was
                         sent (the response is lost mid-flight)
- ``partial_frame``    — sidecar client dies mid-send (server reads a
                         truncated frame)
- ``backend_loss``     — the compiled dispatch raises (accelerator gone)
- ``resident_corrupt`` — a device-resident group buffer is corrupted
                         (one element flipped behind the runtime's back)
- ``mirror_drift``     — the host mirror of device truth drifts (one
                         element flipped, so the next value-diff is wrong)
- ``slow_dispatch``    — the dispatch stalls past the cycle deadline
- ``bind_fail``        — a bind dispatch to the cluster API fails once
- ``evict_fail``       — an evict dispatch fails once
- ``lease_expiry``     — the leader lease is stolen by a rival that then
                         lets it expire
- ``process_kill``     — the scheduler/sidecar process dies outright at a
                         kill phase (pre-dispatch, in-flight, post-drain;
                         param picks which) and is restarted from its
                         crash-consistent checkpoint. Performed BY the
                         restart harness (chaos/restart.py) — a SIGKILL
                         is not an exception the runtime's fail-soft
                         handlers could be allowed to swallow — so the
                         injector only arms and logs it.
- ``leader_kill``      — the ACTIVE leader of an HA replica pair dies at
                         a kill phase (param picks which); the warm
                         standby wins the lease and promotes
                         (runtime/replication.py). Harness-performed,
                         like process_kill (chaos/failover.py).
- ``split_brain``      — the deposed leader of a failover does NOT know
                         it lost: it keeps flushing its in-flight writes
                         after the new leader took over. The fencing
                         token must reject every one (zero duplicate
                         binds). Harness-performed.
- ``replication_partition`` — the leader->standby checkpoint stream
                         drops one envelope on the floor (the
                         ``replication.send`` seam); the stream must
                         self-repair and a later failover must still
                         promote decision-identically.
- ``device_loss``      — a device of the serving mesh dies and STAYS
                         dead: every later sharded dispatch whose mesh
                         contains it raises with the device attributed
                         (``ChaosError.device_ids``), until an optional
                         ``heal_after`` revives it. Distinct from the
                         transient ``backend_loss``: this is the
                         persistent fault the elastic-mesh rung
                         (parallel/health.py) exists for — quarantine,
                         shrink to the next pow2 width, regrow on
                         probation (chaos/meshloss.py).
- ``device_flap``      — a device that dies, heals, and dies again every
                         time a regrown mesh readmits it; the health
                         registry's flap damping must bound the re-mesh
                         churn instead of re-meshing every cooldown.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
from typing import Iterable, List, Optional, Tuple

#: every injectable fault kind, in canonical order
FAULT_KINDS = (
    "socket_drop", "partial_frame", "backend_loss", "resident_corrupt",
    "mirror_drift", "slow_dispatch", "bind_fail", "evict_fail",
    "lease_expiry", "process_kill", "leader_kill", "split_brain",
    "replication_partition", "device_loss", "device_flap",
)

#: kinds whose recovery must keep the decision sequence bit-identical to
#: the no-fault run (the sha-matrix acceptance set); socket faults are
#: recoverable too but only fire on the sidecar serving path
RECOVERABLE_KINDS = ("backend_loss", "resident_corrupt", "mirror_drift",
                     "slow_dispatch", "bind_fail", "evict_fail")

#: kinds that model PERSISTENT device loss on the sharded mesh — also
#: decision-neutral (the elastic-mesh rung re-fuses from source truth on
#: the shrunk mesh), but driven by their own probe (chaos/meshloss.py)
#: because they only mean anything when a mesh is serving
PERSISTENT_KINDS = ("device_loss", "device_flap")


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault: fire ``kind`` at scheduling cycle ``cycle``
    (or the first later cycle where its seam becomes reachable), with a
    seed-derived ``param`` the injector uses for kind-specific choices
    (which element to flip, etc.)."""

    kind: str
    cycle: int
    param: int


class FaultPlan:
    """A seed-deterministic fault schedule over ``cycles`` cycles.

    Same (seed, cycles, kinds, per_kind) -> byte-identical schedule:
    the schedule is derived from a private :class:`random.Random` and
    fingerprinted by :meth:`schedule_sha`. Faults are scheduled from
    cycle 1 on — cycle 0 is the cold full-pack/compile cycle, and the
    resident-state faults need a mirror to corrupt.
    """

    def __init__(self, seed: int = 0, cycles: int = 8,
                 kinds: Optional[Iterable[str]] = None, per_kind: int = 1):
        kinds = tuple(kinds) if kinds is not None else FAULT_KINDS
        unknown = [k for k in kinds if k not in FAULT_KINDS]
        if unknown:
            raise ValueError(f"unknown fault kinds: {unknown}")
        if cycles < 2:
            raise ValueError("a fault plan needs at least 2 cycles "
                             "(cycle 0 is the cold full-pack cycle)")
        self.seed = int(seed)
        self.cycles = int(cycles)
        self.kinds = kinds
        rng = random.Random(self.seed)
        faults: List[Fault] = []
        for kind in kinds:
            for _ in range(per_kind):
                faults.append(Fault(kind=kind,
                                    cycle=rng.randrange(1, cycles),
                                    param=rng.randrange(1 << 30)))
        self.faults: Tuple[Fault, ...] = tuple(
            sorted(faults, key=lambda f: (f.cycle, f.kind, f.param)))

    @classmethod
    def explicit(cls, faults: Iterable[Fault], cycles: int = 8,
                 seed: int = 0) -> "FaultPlan":
        """A plan with hand-placed faults instead of seed-derived ones —
        for probes whose acceptance pins an exact sequence (the meshloss
        probe's loss-at-cycle-2-then-cycle-4 shrink ladder). Still
        deterministic and still fingerprinted by schedule_sha()."""
        faults = tuple(faults)
        unknown = [f.kind for f in faults if f.kind not in FAULT_KINDS]
        if unknown:
            raise ValueError(f"unknown fault kinds: {unknown}")
        plan = cls.__new__(cls)
        plan.seed = int(seed)
        plan.cycles = int(cycles)
        plan.kinds = tuple(dict.fromkeys(f.kind for f in faults))
        plan.faults = tuple(sorted(faults,
                                   key=lambda f: (f.cycle, f.kind, f.param)))
        return plan

    def for_cycle(self, cycle: int) -> List[Fault]:
        return [f for f in self.faults if f.cycle == cycle]

    def schedule_sha(self) -> str:
        """sha256 fingerprint of the exact schedule — two plans with the
        same seed/config must agree, which is the determinism contract
        the chaos tests pin."""
        return hashlib.sha256(repr(self.faults).encode()).hexdigest()[:16]

    def __repr__(self) -> str:  # readable in assertion diffs
        return (f"FaultPlan(seed={self.seed}, cycles={self.cycles}, "
                f"faults={list(self.faults)})")
