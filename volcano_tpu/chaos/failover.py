"""Failover probe: leader_kill -> warm-standby promotion -> decision identity.

The HA analog of :mod:`.restart`: a clean single-replica run is compared
against the identical run served by an HA replica pair (leader with a
``LeaderElector`` + a ``WarmStandby`` fed by checkpoint streaming,
runtime/replication.py) interrupted by ``leader_kill`` faults at three
distinct cycle phases —

- ``pre_dispatch``  — leader death between cycles; nothing in flight,
- ``in_flight``     — death with a dispatched-but-undrained pipelined
                      cycle; its decisions die with the leader and the
                      promoted standby re-decides them identically from
                      the same external truth (the one cycle a failover
                      may cost),
- ``post_drain``    — death after the cycle's decisions reached the
                      (external, leader-surviving) cluster truth; the
                      promoted standby re-runs the cycle as a no-op,
                      never re-applying.

Each kill discards the leader Scheduler outright (the harness plays the
OS), advances the shared fake clock past the lease duration so the dead
leader's lease expires, and promotes the standby: its elector's tick
wins the lease — bumping the generation, which IS the fencing token —
and :meth:`WarmStandby.promote` builds the new active scheduler with its
replicated mirrors adopted, so the first post-failover cycle ships a
delta (``cycles_to_steady == 0``).

Identity is judged exactly like the restart probe: the ordered log of
applied bind/evict dispatches plus the final task/podgroup state, sha'd
and compared against the clean run. Three extra legs:

- ``calm``        — the HA pair runs with NO kill: replication on/off
                    must be decision-invisible (the graphcheck claim),
- ``split_brain`` — the deposed leader is kept alive and flushes its
                    in-flight writes AFTER the promotion; every one must
                    be rejected by the fencing token (zero duplicate
                    binds, the applied log unchanged),
- ``partition``   — ``replication_partition`` drops stream envelopes
                    before the kill; the standby promotes from
                    stale-but-intact state and the run must STILL finish
                    decision-identical (the value diff vs external truth
                    self-heals staleness, the same principle that makes
                    a cold promotion decision-correct).
"""

from __future__ import annotations

import contextlib
import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .inject import KILL_PHASES, FaultInjector, chaos, seam
from .plan import Fault, FaultPlan
from .probe import _PROBE_CONF, _churn, _small_cluster

#: virtual-clock base for decision timestamps, matching the chaos probe
_VT = 1000.0

#: default kill matrix: every phase exercised once, spread across the run
_DEFAULT_KILLS = ((2, "pre_dispatch"), (4, "in_flight"), (6, "post_drain"))


class _Clock:
    """Shared fake monotonic clock for every elector in a probe run —
    lease expiry is driven by explicit advances, never by wall time."""

    def __init__(self, now: float = 100.0):
        self.now = float(now)

    def __call__(self) -> float:
        return self.now


def _probe_conf(use_pallas: Optional[str]) -> str:
    """The probe policy, optionally on the pallas kernel path
    (``use_pallas: interpret`` runs the same kernel in interpreter mode —
    any N, CPU-friendly)."""
    if use_pallas is None:
        return _PROBE_CONF
    return f"use_pallas: {use_pallas}\n" + _PROBE_CONF


def _instrument(cluster) -> Tuple[List[tuple], List[object]]:
    """Fence-aware applied-decision log: what the scheduler DID to the
    external world (the restart probe's wrappers are single-arg; the HA
    path threads ``fence=`` through, so these accept it). Also keeps
    every attempted BindIntent — the split-brain leg replays the
    deposed leader's last one against the advanced fence."""
    applied: List[tuple] = []
    intents: List[object] = []
    orig_bind, orig_evict = cluster.bind, cluster.evict

    def bind(intent, fence=None):
        intents.append(intent)
        ok = orig_bind(intent, fence=fence)
        if ok:
            applied.append(("bind", intent.task_uid, intent.node_name,
                            int(getattr(intent, "gpu_index", -1) or 0)))
        return ok

    def evict(intent, fence=None):
        ok = orig_evict(intent, fence=fence)
        if ok:
            applied.append(("evict", intent.task_uid))
        return ok

    cluster.bind = bind
    cluster.evict = evict
    return applied, intents


def _final_state(cluster) -> tuple:
    ci = cluster.ci
    tasks = sorted((t.uid, str(t.status), t.node_name or "")
                   for job in ci.jobs.values()
                   for t in job.tasks.values())
    phases = sorted((uid, str(j.pod_group_phase))
                    for uid, j in ci.jobs.items())
    return (tasks, phases)


def run_failover_probe(seed: int = 7, cycles: int = 8,
                       pipeline: bool = True,
                       kills: Optional[Sequence[Tuple[int, str]]] = None,
                       split_brain_leg: bool = True,
                       partition_leg: bool = True,
                       use_pallas: Optional[str] = None
                       ) -> Dict[str, object]:
    """Run the probe; returns a JSON-ready failover report.

    ``kills`` is a sequence of (cycle, phase) pairs; the default matrix
    exercises all three phases. Kill and split-brain schedules are armed
    through a FaultPlan/FaultInjector (``leader_kill`` / ``split_brain``
    kinds consumed at the ``harness.failover`` seam), so the fired log
    and schedule sha follow the replayable-chaos contract."""
    from ..framework.conf import parse_conf
    from ..metrics import METRICS
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.leader import DEFAULT_LEASE_DURATION, LeaderElector
    from ..runtime.replication import replica_pair
    from ..runtime.scheduler import Scheduler
    from ..runtime.system import VolcanoSystem

    conf = parse_conf(_probe_conf(use_pallas))
    base = _small_cluster()
    kills = tuple(kills) if kills is not None else tuple(
        (c, p) for c, p in _DEFAULT_KILLS if c < cycles)
    bad = [p for _, p in kills if p not in KILL_PHASES]
    if bad:
        raise ValueError(f"unknown kill phases: {bad}")

    def make_injector(kill_kind: str, kill_list, extra=()):
        plan = FaultPlan(seed=seed, cycles=cycles, kinds=())
        plan.faults = tuple(sorted(
            [Fault(kind=kill_kind, cycle=c,
                   param=KILL_PHASES.index(p)) for c, p in kill_list]
            + list(extra),
            key=lambda f: (f.cycle, f.kind, f.param)))
        return plan, FaultInjector(plan)

    def run(ha: bool, kill_kind: Optional[str] = None,
            kill_list=(), extra_faults=()):
        """One probe timeline. ``ha=False`` is the plain single-replica
        clean run; ``ha=True`` wires elector + replication, and
        ``kill_kind`` selects what the armed faults do at the harness
        seam (``leader_kill``: drop + promote; ``split_brain``: promote
        AND let the deposed leader flush)."""
        cluster = FakeCluster(base.clone())
        applied, intents = _instrument(cluster)
        clock = _Clock()
        api = VolcanoSystem().api
        elector = None
        if ha:
            elector = LeaderElector(api, identity="leader-0", clock=clock)
            elector.tick()          # acquire before the first cycle
        sched = Scheduler(cluster, conf=conf, pipeline=pipeline,
                          elector=elector)
        sender = standby = None
        if ha:
            sender, standby = replica_pair(sched, conf)
        promotions: List[dict] = []
        split_checks: List[dict] = []
        standby_n = [0]
        plan = injector = None
        if kill_kind is not None:
            plan, injector = make_injector(kill_kind, kill_list,
                                           extra_faults)
        kill_map: Dict[int, List[str]] = {}
        for c, p in kill_list:
            kill_map.setdefault(c, []).append(p)

        def kill_promote(phase: str, c: int, keep_deposed: bool):
            """The leader death + warm-standby promotion. Returns
            (new_sched, deposed-or-None)."""
            nonlocal sched, sender, standby
            deposed = sched
            # the dead leader stops renewing; its lease must EXPIRE
            # before the standby's tick can win it (the fencing window)
            clock.now += DEFAULT_LEASE_DURATION + 1.0
            standby_n[0] += 1
            el = LeaderElector(api, identity=f"standby-{standby_n[0]}",
                               clock=clock)
            t0 = time.time()
            sched = standby.promote(cluster, conf=conf, pipeline=pipeline,
                                    now=_VT + c, elector=el)
            promote_ms = round((time.time() - t0) * 1000, 3)
            promotions.append(dict(
                cycle=c, phase=phase, promote_ms=promote_ms,
                generation=el.generation,
                seq=standby.applied_seq))
            # the promoted leader streams to a FRESH standby; the old
            # replica object became the leader
            sender, standby = replica_pair(sched, conf)
            return deposed if keep_deposed else None

        ctx = chaos(injector) if injector is not None \
            else contextlib.nullcontext()
        cycles_lost = 0
        with ctx:
            for c in range(cycles):
                if injector is not None:
                    injector.begin_cycle(c)
                clock.now += 1.0
                deposed = None
                for phase in ("pre_dispatch",):
                    if seam("harness.failover", kind=kill_kind,
                            phase=phase) is not None:
                        kill_promote(phase, c, keep_deposed=False)
                out = sched.run_once(now=_VT + c)
                if pipeline and seam("harness.failover", kind=kill_kind,
                                     phase="in_flight") is not None:
                    # the dispatched-but-undrained cycle dies with the
                    # leader (split_brain: survives IN the deposed
                    # object, to be flushed late); the promoted standby
                    # re-decides it from the same truth — the one cycle
                    # a failover may cost
                    deposed = kill_promote(
                        "in_flight", c,
                        keep_deposed=(kill_kind == "split_brain"))
                    cycles_lost += 1
                    out = sched.run_once(now=_VT + c)
                if pipeline:
                    sched.drain(now=_VT + c)
                if deposed is not None:
                    # split brain: the deposed leader flushes its
                    # in-flight cycle AFTER the new leader applied its
                    # re-decision — every write must bounce off the fence
                    before = (len(applied), len(cluster.binds),
                              len(cluster.fenced_rejections))
                    deposed.drain(now=_VT + c)
                    # ...and its retry loop re-sends the most recent
                    # bind it ever dispatched, stamped with its stale
                    # token. The intent itself is perfectly well-formed;
                    # only the fence stands between it and a double
                    # bind, so the rejection must be structural.
                    replay_rejected = None
                    if intents:
                        replay_rejected = not cluster.bind(
                            intents[-1],
                            fence=deposed.elector.generation)
                    split_checks.append(dict(
                        cycle=c,
                        applied_by_deposed=len(applied) - before[0],
                        duplicate_binds=len(cluster.binds) - before[1],
                        fenced_rejections=(len(cluster.fenced_rejections)
                                           - before[2]),
                        replay_rejected=replay_rejected,
                        deposed_generation=deposed.elector.generation,
                        fence_generation=cluster.fence_generation))
                if seam("harness.failover", kind=kill_kind,
                        phase="post_drain") is not None:
                    # this cycle's decisions already reached external
                    # truth; the promoted standby re-runs it as a no-op
                    kill_promote("post_drain", c, keep_deposed=False)
                    sched.run_once(now=_VT + c)
                    if pipeline:
                        sched.drain(now=_VT + c)
                if sender is not None:
                    sender.stream()
                _churn(cluster, c)
        sha = hashlib.sha256(
            repr((applied, _final_state(cluster))).encode()).hexdigest()[:16]
        return dict(sha=sha, promotions=promotions, sched=sched,
                    plan=plan, injector=injector, cluster=cluster,
                    split_checks=split_checks, cycles_lost=cycles_lost,
                    link=(sender.link if sender is not None else None))

    clean = run(ha=False)
    calm = run(ha=True)

    warm0 = METRICS.counter_value("failover_promotions_total",
                                  {"outcome": "warm"})
    kill = run(ha=True, kill_kind="leader_kill", kill_list=kills)
    promote_ms = sorted(p["promote_ms"] for p in kill["promotions"])
    kinds = [e.get("cycle_kind") for e in kill["sched"].flight.snapshots()]
    cycles_to_steady = next(
        (i for i, k in enumerate(kinds) if k == "delta"), None)
    report: Dict[str, object] = {
        "seed": seed,
        "cycles": cycles,
        "pipeline": pipeline,
        "use_pallas": use_pallas,
        "kills": [[c, p] for c, p in kills],
        "kill_schedule_sha": kill["plan"].schedule_sha(),
        "fault_log": [list(f) for f in kill["injector"].fired],
        "clean_sha": clean["sha"],
        "calm_sha": calm["sha"],
        "calm_equal_clean": calm["sha"] == clean["sha"],
        "decisions_sha": kill["sha"],
        "decisions_equal_clean": kill["sha"] == clean["sha"],
        "promotions": kill["promotions"],
        "promote_ms_p50": (promote_ms[len(promote_ms) // 2]
                           if promote_ms else None),
        "warm_promotions": METRICS.counter_value(
            "failover_promotions_total", {"outcome": "warm"}) - warm0,
        "cycles_lost": kill["cycles_lost"],
        "cycles_to_steady": cycles_to_steady,
        "fenced_writes_rejected": len(
            kill["cluster"].fenced_rejections),
    }
    if split_brain_leg:
        sb_kills = tuple((c, p) for c, p in kills if p == "in_flight") \
            or ((min(3, cycles - 1), "in_flight"),)
        sb = run(ha=True, kill_kind="split_brain", kill_list=sb_kills)
        checks = sb["split_checks"]
        report["split_brain"] = {
            "decisions_sha": sb["sha"],
            "decisions_equal_clean": sb["sha"] == clean["sha"],
            "fenced_writes_rejected": sum(
                ch["fenced_rejections"] for ch in checks),
            "applied_by_deposed": sum(
                ch["applied_by_deposed"] for ch in checks),
            "duplicate_binds": sum(
                ch["duplicate_binds"] for ch in checks),
            "replays_rejected": bool(checks) and all(
                ch["replay_rejected"] is True for ch in checks),
            "checks": checks,
        }
    if partition_leg:
        # drop stream envelopes on cycles before a late in_flight kill:
        # the standby promotes from stale-but-intact state and the run
        # must still finish decision-identical
        pk = min(max(2, cycles - 2), cycles - 1)
        drops = tuple(Fault(kind="replication_partition", cycle=c,
                            param=c) for c in (1, pk - 1) if 0 < c < pk)
        lost0 = METRICS.counter_value("replication_envelopes_total",
                                      {"result": "lost"})
        part = run(ha=True, kill_kind="leader_kill",
                   kill_list=((pk, "in_flight"),) if pipeline
                   else ((pk, "pre_dispatch"),),
                   extra_faults=drops)
        report["partition"] = {
            "decisions_sha": part["sha"],
            "decisions_equal_clean": part["sha"] == clean["sha"],
            "envelopes_dropped": METRICS.counter_value(
                "replication_envelopes_total", {"result": "lost"}) - lost0,
            "promotions": part["promotions"],
        }
    return report
