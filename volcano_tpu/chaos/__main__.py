"""``python -m volcano_tpu.chaos --smoke`` — the tier-1 chaos smoke.

Runs a small seeded fault storm (every recoverable fault kind once) over a
multi-cycle pipelined scheduler run on the current backend and verifies:

- the run COMPLETES (every fault recovered, the loop kept serving),
- the decision sha equals the no-fault run's (recoverable faults are
  decision-neutral),
- the planted resident-state corruption tripped the integrity digest.

``--restart`` runs the restart smoke instead (chaos/restart.py): kill the
scheduler at all three process_kill phases mid-run, restore each time from
the crash-consistent checkpoint, and verify the applied-decision log
matches the uninterrupted run — including a corrupt-checkpoint leg that
must land on the ``fallback`` ladder rung and STILL finish identical.

``--spec`` runs the speculation smoke instead (chaos/spec.py): the depth-k
sha-matrix — sync vs depth-1 vs depth-k decision streams over settled and
late-arrival workloads must be bit-identical, with at least one
speculative cycle actually invalidated and replayed, on the scan AND
pallas-interpret allocate paths, plus sidecar serving-ring payload
identity at depth k.

``--failover`` runs the HA smoke instead (chaos/failover.py): kill the
leader at all three phases, promote the warm standby each time, and verify
the promotion lands warm (``cycles_to_steady == 0``), the decisions stay
sha-identical to the uninterrupted run at a cost of at most one cycle, and
the split-brain leg's deposed-leader writes are rejected by the fencing
token — not applied.

``--meshloss`` runs the elastic-mesh smoke instead (chaos/meshloss.py):
persistent ``device_loss`` faults on an 8-device CPU mesh must
quarantine, shrink the serving mesh 8 -> 4 -> 2, regrow to 8 after
probation, and keep the decision sha bit-identical to the clean run on
the scan AND pallas-interpret sharded cycles; a ``device_flap`` leg
proves the probation backoff bounds re-mesh churn under a device that
re-dies on every readmission.

Exit 0 on success, 1 on any violated claim, 2 on harness error. The JSON
report prints either way so CI logs carry the evidence.
"""

from __future__ import annotations

import argparse
import json
import sys


def _restart_smoke(args) -> int:
    from .restart import run_restart_probe
    try:
        report = run_restart_probe(seed=args.seed,
                                   cycles=max(args.cycles, 8))
    except Exception as e:  # harness failure, not a chaos verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report, indent=2, default=str))
    corrupt = report.get("corrupt") or {}
    ok = (report["decisions_equal_clean"]
          and report["restore_outcomes"].get("restored", 0) >= 3
          and len({p for _, p in report["kills"]}) >= 3
          and corrupt.get("decisions_equal_clean", False)
          and corrupt.get("fallbacks_visible", 0) >= 1)
    if not ok:
        print("restart smoke FAILED: "
              + ("decision log diverged from the clean run; "
                 if not report["decisions_equal_clean"] else "")
              + ("not every kill restored; "
                 if report["restore_outcomes"].get("restored", 0) < 3
                 else "")
              + ("corrupt-checkpoint leg diverged; "
                 if not corrupt.get("decisions_equal_clean", False) else "")
              + ("fallback outcome never counted"
                 if corrupt.get("fallbacks_visible", 0) < 1 else ""),
              file=sys.stderr)
    return 0 if ok else 1


def _failover_smoke(args) -> int:
    from .failover import run_failover_probe
    try:
        report = run_failover_probe(seed=args.seed,
                                    cycles=max(args.cycles, 8))
    except Exception as e:  # harness failure, not a chaos verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report, indent=2, default=str))
    sb = report.get("split_brain") or {}
    part = report.get("partition") or {}
    ok = (report["calm_equal_clean"]
          and report["decisions_equal_clean"]
          and len({p for _, p in report["kills"]}) >= 3
          and report["cycles_lost"] <= 1
          and report["cycles_to_steady"] == 0
          and sb.get("decisions_equal_clean", False)
          and sb.get("fenced_writes_rejected", 0) >= 1
          and sb.get("applied_by_deposed", 1) == 0
          and sb.get("duplicate_binds", 1) == 0
          and part.get("decisions_equal_clean", False))
    if not ok:
        print("failover smoke FAILED: "
              + ("replication was not decision-invisible; "
                 if not report["calm_equal_clean"] else "")
              + ("decision log diverged from the clean run; "
                 if not report["decisions_equal_clean"] else "")
              + ("not every kill phase exercised; "
                 if len({p for _, p in report["kills"]}) < 3 else "")
              + ("failover cost more than one cycle; "
                 if report["cycles_lost"] > 1 else "")
              + ("promotion landed cold, not warm; "
                 if report["cycles_to_steady"] != 0 else "")
              + ("split-brain leg diverged; "
                 if not sb.get("decisions_equal_clean", False) else "")
              + ("deposed leader's writes were not fence-rejected; "
                 if sb.get("fenced_writes_rejected", 0) < 1
                 or sb.get("applied_by_deposed", 1) != 0
                 or sb.get("duplicate_binds", 1) != 0 else "")
              + ("partition leg diverged"
                 if not part.get("decisions_equal_clean", False) else ""),
              file=sys.stderr)
    return 0 if ok else 1


def _meshloss_smoke(args) -> int:
    from .meshloss import (check_flap_leg, check_loss_leg,
                           run_meshloss_probe)
    try:
        legs = {
            "loss_scan": run_meshloss_probe(seed=args.seed),
            "loss_pallas_interpret": run_meshloss_probe(
                seed=args.seed, use_pallas="interpret"),
            "flap_scan": run_meshloss_probe(seed=args.seed, flap=True),
        }
    except Exception as e:  # harness failure, not a chaos verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    failures = (check_loss_leg(legs["loss_scan"])
                + check_loss_leg(legs["loss_pallas_interpret"])
                + check_flap_leg(legs["flap_scan"]))
    report = {"legs": legs, "failures": failures, "ok": not failures}
    print(json.dumps(report, indent=2, default=str))
    if failures:
        print("meshloss smoke FAILED: " + "; ".join(failures),
              file=sys.stderr)
    return 0 if not failures else 1


def _spec_smoke(args) -> int:
    from .spec import run_spec_matrix
    try:
        report = run_spec_matrix(depth=args.depth)
    except Exception as e:  # harness failure, not a chaos verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report, indent=2, default=str))
    if not report["ok"]:
        bad = [f"{b}/{w}" for b, legs in report["backends"].items()
               for w in ("workload_a", "workload_b")
               if not legs[w]["equal"]]
        print("speculation smoke FAILED: "
              + (f"decision sha diverged across modes ({', '.join(bad)}); "
                 if bad else "")
              + ("no replay ever fired (speculation untested); "
                 if not all(l["replayed"]
                            for l in report["backends"].values()) else "")
              + ("scan and pallas-interpret disagree; "
                 if not report["backends_agree"] else "")
              + ("sidecar depth-k payload stream diverged"
                 if not (report.get("sidecar") or {}).get(
                     "payloads_equal", True) else ""),
              file=sys.stderr)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos smoke: seeded fault storm + recovery check")
    parser.add_argument("--smoke", action="store_true",
                        help="run the fast tier-1 smoke plan")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--cycles", type=int, default=6)
    parser.add_argument("--spec", action="store_true",
                        help="run the depth-k speculation sha-matrix "
                             "(chaos/spec.py): sync vs depth-1 vs depth-k "
                             "with replayed late-arrival invalidations, "
                             "scan + pallas-interpret + sidecar legs")
    parser.add_argument("--depth", type=int, default=3,
                        help="in-flight depth for the --spec k legs")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-cycle watchdog deadline (default: off — "
                             "CI machines vary too much for a fixed one)")
    parser.add_argument("--sharded", action="store_true",
                        help="run the storm on the node-axis sharded "
                             "backend (conf sharding: true)")
    parser.add_argument("--pallas-interpret", action="store_true",
                        help="run the storm on the pallas kernel path in "
                             "interpret mode (conf use_pallas: interpret); "
                             "with --sharded this is the shard-local "
                             "candidate launch")
    parser.add_argument("--wave", type=int, nargs="?", const=4, default=None,
                        metavar="W",
                        help="run the storm on the wavefront placement "
                             "path (conf wave_width: W, default 4): "
                             "faults land mid-wave and decisions must "
                             "still equal the clean run")
    parser.add_argument("--restart", action="store_true",
                        help="run the restart smoke: process_kill at "
                             "every phase, checkpoint restore, decision "
                             "identity vs the uninterrupted run")
    parser.add_argument("--meshloss", action="store_true",
                        help="run the elastic-mesh smoke: persistent "
                             "device_loss shrinks the 8-dev CPU mesh "
                             "8->4->2, probation regrows to 8, decisions "
                             "stay sha-identical on scan AND pallas-"
                             "interpret, and a device_flap leg proves "
                             "damping bounds the re-mesh churn")
    parser.add_argument("--failover", action="store_true",
                        help="run the HA smoke: leader_kill at every "
                             "phase, warm-standby promotion, fence-"
                             "rejected split-brain writes, decision "
                             "identity vs the uninterrupted run")
    args = parser.parse_args(argv)
    if args.spec:
        return _spec_smoke(args)
    if args.restart:
        return _restart_smoke(args)
    if args.failover:
        return _failover_smoke(args)
    if args.meshloss:
        return _meshloss_smoke(args)
    from . import run_chaos_probe
    try:
        report = run_chaos_probe(seed=args.seed, cycles=args.cycles,
                                 deadline_ms=args.deadline_ms,
                                 sharding=args.sharded,
                                 use_pallas=("interpret"
                                             if args.pallas_interpret
                                             else None),
                                 wave_width=args.wave)
    except Exception as e:  # harness failure, not a chaos verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report, indent=2, default=str))
    ok = (report["decisions_equal_clean"]
          and report["faults_fired"] > 0
          and report["digest_mismatches"] >= 1)
    if not ok:
        print("chaos smoke FAILED: "
              + ("decision sha diverged from the clean run; "
                 if not report["decisions_equal_clean"] else "")
              + ("no faults fired; " if report["faults_fired"] == 0 else "")
              + ("integrity digest never tripped"
                 if report["digest_mismatches"] < 1 else ""),
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
