"""``python -m volcano_tpu.chaos --smoke`` — the tier-1 chaos smoke.

Runs a small seeded fault storm (every recoverable fault kind once) over a
multi-cycle pipelined scheduler run on the current backend and verifies:

- the run COMPLETES (every fault recovered, the loop kept serving),
- the decision sha equals the no-fault run's (recoverable faults are
  decision-neutral),
- the planted resident-state corruption tripped the integrity digest.

Exit 0 on success, 1 on any violated claim, 2 on harness error. The JSON
report prints either way so CI logs carry the evidence.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="chaos smoke: seeded fault storm + recovery check")
    parser.add_argument("--smoke", action="store_true",
                        help="run the fast tier-1 smoke plan")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--cycles", type=int, default=6)
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-cycle watchdog deadline (default: off — "
                             "CI machines vary too much for a fixed one)")
    parser.add_argument("--sharded", action="store_true",
                        help="run the storm on the node-axis sharded "
                             "backend (conf sharding: true)")
    args = parser.parse_args(argv)
    from . import run_chaos_probe
    try:
        report = run_chaos_probe(seed=args.seed, cycles=args.cycles,
                                 deadline_ms=args.deadline_ms,
                                 sharding=args.sharded)
    except Exception as e:  # harness failure, not a chaos verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    print(json.dumps(report, indent=2, default=str))
    ok = (report["decisions_equal_clean"]
          and report["faults_fired"] > 0
          and report["digest_mismatches"] >= 1)
    if not ok:
        print("chaos smoke FAILED: "
              + ("decision sha diverged from the clean run; "
                 if not report["decisions_equal_clean"] else "")
              + ("no faults fired; " if report["faults_fired"] == 0 else "")
              + ("integrity digest never tripped"
                 if report["digest_mismatches"] < 1 else ""),
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
