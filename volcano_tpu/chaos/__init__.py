"""Fault injection + recovery verification for the cycle runtime (ISSUE 5).

Three pieces:

- :mod:`.plan` — :class:`FaultPlan`: a seed-deterministic schedule of
  faults (kind, cycle, parameter). Same seed, same storm, every time.
- :mod:`.inject` — :class:`FaultInjector` and the :func:`seam` hook the
  runtime calls at its real failure seams (compiled dispatch, the
  device-resident delta path, cluster bind/evict dispatch, sidecar
  framing, the leader lease). Near-zero cost when no injector is
  installed.
- :mod:`.probe` — :func:`run_chaos_probe`: a fault storm over a
  multi-cycle scheduler run compared against the clean run, shared by
  the tier-1 smoke CLI (``python -m volcano_tpu.chaos --smoke``) and
  bench.py's ``robustness`` block.
- :mod:`.restart` — :func:`run_restart_probe`: the ``process_kill``
  storm (ISSUE 10): kill the scheduler at pre-dispatch / in-flight /
  post-drain phases, restore from the crash-consistent checkpoint
  (:mod:`..runtime.checkpoint`), and prove the applied-decision log
  matches the uninterrupted run — tier-1 smoke
  ``python -m volcano_tpu.chaos --smoke --restart`` and bench.py's
  ``restart`` block.
- :mod:`.failover` — :func:`run_failover_probe`: the HA storm
  (ISSUE 11): ``leader_kill`` at every phase promotes the warm standby
  (:mod:`..runtime.replication`) and the run must stay decision-
  identical costing at most one cycle; ``split_brain`` lets the deposed
  leader flush late and every write must bounce off the lease-
  generation fence; ``replication_partition`` drops stream envelopes
  and the stale promotion must self-heal — tier-1 smoke
  ``python -m volcano_tpu.chaos --smoke --failover`` and bench.py's
  ``failover`` block.
- :mod:`.meshloss` — :func:`run_meshloss_probe`: the elastic-mesh storm
  (ISSUE 20): persistent ``device_loss`` faults quarantine devices and
  shrink the sharded serving mesh 8 -> 4 -> 2, probation regrows it to
  full width, and the decision sha must stay bit-identical to the clean
  unshrunk run on scan AND pallas-interpret; a ``device_flap`` leg
  proves the stateful backoff damps re-mesh churn — tier-1 smoke
  ``python -m volcano_tpu.chaos --smoke --meshloss`` and bench.py's
  ``robustness`` block.

The hardening the faults exercise lives where it belongs: the in-graph
integrity digest and mirror-rebuild recovery in :mod:`..ops.fused_io`,
the device-health registry and health-aware mesh selection in
:mod:`..parallel.health` / :mod:`..parallel.sharding`, the
pipelined -> sync -> elastic-mesh -> cpu-oracle degradation ladder in
:mod:`..runtime.scheduler`, and the reconnect/idempotent-replay protocol
in :mod:`..runtime.sidecar` — see docs/architecture.md "Fault tolerance
& degradation ladder".
"""

from __future__ import annotations

from .failover import run_failover_probe
from .inject import (KILL_PHASES, ChaosError, FaultInjector, active, chaos,
                     install, seam, uninstall)
from .meshloss import run_meshloss_probe
from .plan import (FAULT_KINDS, PERSISTENT_KINDS, RECOVERABLE_KINDS, Fault,
                   FaultPlan)
from .probe import run_chaos_probe
from .restart import run_restart_probe

__all__ = [
    "FAULT_KINDS", "RECOVERABLE_KINDS", "PERSISTENT_KINDS", "KILL_PHASES",
    "Fault", "FaultPlan", "FaultInjector", "ChaosError", "seam", "active",
    "install", "uninstall", "chaos", "run_chaos_probe", "run_restart_probe",
    "run_failover_probe", "run_meshloss_probe",
]
