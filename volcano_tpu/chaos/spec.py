"""Depth-k speculation sha-matrix: the PR 5 chaos methodology applied to
the speculative pipeline.

The depth-k ring (runtime/scheduler.py) dispatches cycles against the
last-drained snapshot and REPLAYS any cycle whose input epoch a
predecessor's applied decisions invalidated. The claim that makes that
safe is decision-neutrality: over the same external event schedule the
decision stream is bit-identical whether the loop runs synchronously,
one deep, or k deep with speculation and replay. This module is the
executable form of that claim, exercised on both allocate backends
(pure-XLA scan and pallas-interpret) plus the sidecar's serving ring.

Two workloads, same cluster, fixed event schedule:

- **A (settled churn)** — probe-style churn bursts (bound→running, gang
  complete+re-arrive, node add/remove, job arrival) land at BARRIER
  cycles: the driver drains the ring before applying them, the way a
  production loop quiesces before acting on feedback-coupled state.
  Between bursts the pipeline runs speculative cycles; the binds each
  burst provokes invalidate whatever is in flight, so replays fire and
  must reproduce the synchronous decisions exactly.
- **B (late arrivals)** — workload A plus structural arrivals injected
  MID-FLIGHT (no barrier): a new job and a new node land while
  speculative cycles are in the ring. Arrivals apply at cycle
  boundaries, so dispatch visibility is identical to the sync loop; the
  first cycle to bind the new work invalidates its in-flight successors
  and the replays must again be decision-neutral. Injection points
  follow quiet windows longer than the ring depth — an arrival landing
  while an already-doomed speculation awaits replay would be visible to
  the replay but not to the sync run, which is a DRIVER ordering bug,
  not a scheduler property (production quiesces exactly like workload
  A's barriers when it cannot guarantee the gap).

Matrix legs per backend: sync / depth-1 / depth-k on A (three-way sha
equality), sync / depth-k on B (equality plus ``cycle_replays_total``
strictly positive — speculation must actually have been invalidated).
The sidecar leg replays the same snapshot sequence through
``schedule_buffer_pipelined`` at depth 1 and depth k and requires the
payload streams byte-identical.

``python -m volcano_tpu.chaos --smoke --spec`` runs this as the tier-1
speculation smoke (scripts/tier1.sh, TIER1_SKIP_SPEC=1 skips).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

from . import probe

#: default in-flight depth for the k legs (>= 2 or nothing speculates)
DEFAULT_DEPTH = 3
#: default cycles per leg; event schedule below assumes >= 24
DEFAULT_CYCLES = 28
#: barrier-churn cycles (workload A and B)
BARRIER_CYCLES = (4, 12, 20)
#: mid-flight arrival cycles (workload B only). Each sits at least
#: depth+2 cycles past the previous structural event, so every
#: speculation the event doomed has already been replayed and drained —
#: an arrival landing earlier would be visible to a pre-arrival cycle's
#: replay but not to its sync counterpart (see the module docstring)
ARRIVAL_CYCLES = (9, 17)


def _node(name: str):
    from ..api import NodeInfo, Resource
    return NodeInfo(name, allocatable=Resource.from_resource_list(
        {"cpu": "8", "memory": "16Gi", "pods": "110"}))


def _job(uid: str, created: float):
    from ..api import JobInfo, PodGroupPhase, Resource, TaskInfo
    name = uid.split("/", 1)[1]
    job = JobInfo(uid=uid, name=name, namespace="default", queue="default",
                  min_available=2, priority=1, creation_timestamp=created,
                  pod_group_phase=PodGroupPhase.INQUEUE)
    for t in range(3):
        job.add_task(TaskInfo(
            uid=f"{uid}-t{t}", name=f"{name}-t{t}", namespace="default",
            resreq=Resource.from_resource_list(
                {"cpu": "2", "memory": "2Gi"})))
    return job


def _barrier_churn(cluster, c: int) -> None:
    """Feedback-coupled churn (reads bind/run state), barrier-applied."""
    probe._churn(cluster, c)
    if c == BARRIER_CYCLES[-1]:
        # retire every job on the arrival node, then the node itself —
        # the structural remove leg of the matrix
        ci = cluster.ci
        for uid in sorted(u for u, j in ci.jobs.items()
                          if any(t.node_name == "nx-spec"
                                 for t in j.tasks.values())):
            cluster.remove_job(uid)
        cluster.remove_node("nx-spec")


def _arrival(cluster, c: int) -> None:
    """Pure external arrivals — safe to land mid-flight."""
    if c == ARRIVAL_CYCLES[0]:
        cluster.add_node(_node("nx-spec"))
    else:
        job = _job(f"default/jx-spec{c}", float(c))
        cluster.ci.add_job(job)
        cluster.mark_dirty(job_uid=job.uid, structural=True)


def _drive(depth: int, pipeline: bool, cycles: int, arrivals: bool,
           conf_extra: str = "") -> Dict[str, object]:
    """One matrix leg: drive the schedule, collect every completed
    cycle's decision digest IN DISPATCH ORDER, and sha the stream."""
    from ..framework.conf import parse_conf
    from ..metrics import METRICS
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler
    conf = parse_conf(
        probe._PROBE_CONF + conf_extra
        + (f"pipeline: true\npipeline_depth: {depth}\n" if pipeline else ""))
    cluster = FakeCluster(probe._small_cluster().clone())
    sched = Scheduler(cluster, conf=conf)
    digests: List[tuple] = []

    def collect(rec) -> None:
        # pipelined priming cycles return the live (undrained) session;
        # its decisions surface later, through the ring
        if rec is None or (pipeline and hasattr(rec, "dispatch_allocate")):
            return
        digests.append(probe._cycle_digest(rec))

    replays0 = METRICS.counter_total("cycle_replays_total")
    for c in range(cycles):
        if c in BARRIER_CYCLES:
            while sched._ring:          # quiesce before feedback churn
                collect(sched._drain_pending(1000.0 + c))
            _barrier_churn(cluster, c)
        if arrivals and c in ARRIVAL_CYCLES:
            _arrival(cluster, c)        # mid-flight, no barrier
        collect(sched.run_once(now=1000.0 + c))
    while sched._ring:
        collect(sched._drain_pending(1000.0 + cycles))
    return {
        "sha": hashlib.sha256(repr(digests).encode()).hexdigest()[:16],
        "records": len(digests),
        "replays": int(METRICS.counter_total("cycle_replays_total")
                       - replays0),
        "degradation": sched.degradation_level,
    }


def _sidecar_leg(depth: int, rounds: int = 6) -> Dict[str, object]:
    """Serving-ring leg: the same snapshot sequence through the sidecar
    at depth 1 and depth k must yield byte-identical payload streams."""
    import struct
    from ..native.wire import serialize
    from ..runtime.sidecar import SchedulerSidecar
    bufs = []
    for r in range(rounds):
        ci = probe._small_cluster()
        for j, uid in enumerate(sorted(ci.jobs)):
            ci.jobs[uid].priority = (j + r) % 5
        bufs.append(serialize(ci)[0])

    def serve(d: int) -> List[bytes]:
        sc = SchedulerSidecar(conf=probe._PROBE_CONF
                              + f"pipeline_depth: {d}\n")
        payloads = []
        for buf in bufs:
            p = sc.schedule_buffer_pipelined(buf)
            if struct.unpack("<II", p[4:12]) != (0, 0):
                payloads.append(p)
        while True:
            p = sc.drain_pending()
            if p is None:
                break
            payloads.append(p)
        return payloads

    shallow, deep = serve(1), serve(depth)
    return {"rounds": rounds,
            "payloads_equal": shallow == deep,
            "payloads": len(shallow)}


def run_spec_matrix(depth: int = DEFAULT_DEPTH,
                    cycles: int = DEFAULT_CYCLES,
                    backends: Optional[List[str]] = None,
                    sidecar: bool = True) -> Dict[str, object]:
    """Run the full matrix; returns a JSON-ready report with ``ok``."""
    depth = max(2, int(depth))
    backends = list(backends) if backends else ["scan", "pallas_interpret"]
    conf_extra = {"scan": "", "pallas_interpret": "use_pallas: interpret\n"}
    report: Dict[str, object] = {"depth": depth, "cycles": int(cycles),
                                 "backends": {}}
    ok = True
    shas_a = []
    for backend in backends:
        extra = conf_extra[backend]
        a = {mode: _drive(d, p, cycles, arrivals=False, conf_extra=extra)
             for mode, (d, p) in (("sync", (1, False)),
                                  ("depth1", (1, True)),
                                  ("depthk", (depth, True)))}
        b = {mode: _drive(d, p, cycles, arrivals=True, conf_extra=extra)
             for mode, (d, p) in (("sync", (1, False)),
                                  ("depthk", (depth, True)))}
        a_equal = len({leg["sha"] for leg in a.values()}) == 1
        b_equal = len({leg["sha"] for leg in b.values()}) == 1
        replayed = (a["depthk"]["replays"] + b["depthk"]["replays"]) > 0
        shas_a.append(a["sync"]["sha"])
        report["backends"][backend] = {
            "workload_a": dict(a, equal=a_equal),
            "workload_b": dict(b, equal=b_equal),
            "replayed": replayed,
        }
        ok = ok and a_equal and b_equal and replayed
    # the two allocate backends must agree with each other too — the
    # repo-wide bit-identical kernel contract, pinned here because a
    # depth bug that broke only one backend would otherwise still pass
    backends_agree = len(set(shas_a)) == 1
    report["backends_agree"] = backends_agree
    ok = ok and backends_agree
    if sidecar:
        report["sidecar"] = _sidecar_leg(depth)
        ok = ok and bool(report["sidecar"]["payloads_equal"])
    report["ok"] = ok
    return report
