"""Restart probe: process_kill -> checkpoint restore -> decision identity.

The restart analog of :mod:`.probe`'s fault storm: a clean multi-cycle
scheduler run is compared against the identical run interrupted by
``process_kill`` faults at three distinct cycle phases —

- ``pre_dispatch``  — death between cycles; nothing in flight,
- ``in_flight``     — death with a dispatched-but-undrained pipelined
                      cycle; its decisions die with the process and the
                      restored scheduler re-decides them identically,
- ``post_drain``    — death after the cycle's decisions were applied to
                      the (external, crash-surviving) cluster truth but
                      before the next checkpoint; the restored scheduler
                      re-runs the cycle as a no-op, never re-applying —
                      the never-double-dispatch half of the claim.

Each kill discards the Scheduler outright (the harness plays the OS: a
SIGKILL is not an exception the runtime's fail-soft handlers could be
allowed to swallow), builds a fresh one over the same cluster, and calls
:meth:`Scheduler.restore` on the last checkpoint. Identity is judged on
what actually reached the cluster: the ordered log of applied bind/evict
dispatches plus the final task/podgroup state — per-cycle scheduler
records would misreport the post-drain case, where the legitimate no-op
re-run cycle exists only in the interrupted timeline.

A ``corrupt`` leg flips a byte in every checkpoint before restoring:
each restore must land on the ``fallback`` ladder rung
(checkpoint_restore_total) and the run must STILL finish
decision-identical — cold re-fuse from external truth is decision-
correct; the checkpoint only restores warmth and counters.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .inject import KILL_PHASES, FaultInjector, chaos, seam
from .plan import Fault, FaultPlan
from .probe import _PROBE_CONF, _churn, _small_cluster

#: virtual-clock base, matching the chaos probe (no wall clock in
#: decision paths)
_VT = 1000.0

#: default kill matrix: every phase exercised once, spread across the run
_DEFAULT_KILLS = ((2, "pre_dispatch"), (4, "in_flight"), (6, "post_drain"))


def _instrument(cluster) -> List[tuple]:
    """Wrap the cluster's bind/evict dispatch with an applied-decision
    log — the ground truth of what the scheduler DID to the external
    world, which is what must stay identical across restarts."""
    applied: List[tuple] = []
    orig_bind, orig_evict = cluster.bind, cluster.evict

    def bind(intent):
        ok = orig_bind(intent)
        if ok:
            applied.append(("bind", intent.task_uid, intent.node_name,
                            int(getattr(intent, "gpu_index", -1) or 0)))
        return ok

    def evict(intent):
        ok = orig_evict(intent)
        if ok:
            applied.append(("evict", intent.task_uid))
        return ok

    cluster.bind = bind
    cluster.evict = evict
    return applied


def _final_state(cluster) -> tuple:
    ci = cluster.ci
    tasks = sorted((t.uid, str(t.status), t.node_name or "")
                   for job in ci.jobs.values()
                   for t in job.tasks.values())
    phases = sorted((uid, str(j.pod_group_phase))
                    for uid, j in ci.jobs.items())
    return (tasks, phases)


def _flip_byte(path: str) -> None:
    """Damage a checkpoint in place: flip the last byte (inside the
    pickled body, so the content sha must catch it)."""
    if not os.path.exists(path):
        return
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))


def _kill_restore(cluster, conf, pipeline, ckpt_path, cycle, phase,
                  restores, corrupt):
    """The kill: the old Scheduler object is simply dropped (its pending
    cycle, session, and residents die with it); a fresh one over the same
    external cluster truth restores from the last checkpoint."""
    from ..runtime.scheduler import Scheduler
    seam("harness.kill", phase=phase)
    if corrupt:
        _flip_byte(ckpt_path)
    t0 = time.time()
    sched = Scheduler(cluster, conf=conf, pipeline=pipeline)
    outcome = sched.restore(ckpt_path, now=_VT + cycle)
    restores.append(dict(cycle=cycle, phase=phase, outcome=outcome,
                         restore_ms=round((time.time() - t0) * 1000, 3)))
    return sched


def run_restart_probe(seed: int = 7, cycles: int = 8, pipeline: bool = True,
                      kills: Optional[Sequence[Tuple[int, str]]] = None,
                      corrupt_leg: bool = True) -> Dict[str, object]:
    """Run the probe; returns a JSON-ready restart report.

    ``kills`` is a sequence of (cycle, phase) pairs; the default matrix
    exercises all three phases. The kill schedule is armed through a
    FaultPlan/FaultInjector (the ``process_kill`` kind), so the fired log
    and schedule sha follow the same replayable-chaos contract as every
    other fault kind."""
    from ..framework.conf import parse_conf
    from ..metrics import METRICS
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler

    conf = parse_conf(_PROBE_CONF)
    base = _small_cluster()
    kills = tuple(kills) if kills is not None else tuple(
        (c, p) for c, p in _DEFAULT_KILLS if c < cycles)
    bad = [p for _, p in kills if p not in KILL_PHASES]
    if bad:
        raise ValueError(f"unknown kill phases: {bad}")

    def make_injector():
        # an explicit schedule in FaultPlan clothing: param selects the
        # phase, so the injector's arm/consume/fired-log machinery (and
        # schedule_sha fingerprint) is the same as any seeded storm
        plan = FaultPlan(seed=seed, cycles=cycles, kinds=())
        plan.faults = tuple(sorted(
            (Fault(kind="process_kill", cycle=c,
                   param=KILL_PHASES.index(p)) for c, p in kills),
            key=lambda f: (f.cycle, f.kind, f.param)))
        return plan, FaultInjector(plan)

    def run(kill_run: bool, corrupt: bool = False):
        cluster = FakeCluster(base.clone())
        applied = _instrument(cluster)
        sched = Scheduler(cluster, conf=conf, pipeline=pipeline)
        restores: List[dict] = []
        plan = injector = None
        ckpt_path = None
        tmpdir = None
        if kill_run:
            plan, injector = make_injector()
            tmpdir = tempfile.TemporaryDirectory(prefix="vckp-probe-")
            ckpt_path = os.path.join(tmpdir.name, "sched.vckp")
        kill_map: Dict[int, List[str]] = {}
        for c, p in kills if kill_run else ():
            kill_map.setdefault(c, []).append(p)
        ctx = chaos(injector) if injector is not None \
            else contextlib.nullcontext()
        with ctx:
            for c in range(cycles):
                if injector is not None:
                    injector.begin_cycle(c)
                phases = set(kill_map.get(c, ()))
                if "pre_dispatch" in phases:
                    sched = _kill_restore(cluster, conf, pipeline,
                                          ckpt_path, c, "pre_dispatch",
                                          restores, corrupt)
                out = sched.run_once(now=_VT + c)
                if pipeline and "in_flight" in phases:
                    # the dispatched-but-undrained cycle dies with the
                    # process; the restored scheduler re-decides it from
                    # the same (unchanged) cluster truth
                    sched = _kill_restore(cluster, conf, pipeline,
                                          ckpt_path, c, "in_flight",
                                          restores, corrupt)
                    out = sched.run_once(now=_VT + c)
                if pipeline:
                    sched.drain(now=_VT + c)
                if "post_drain" in phases:
                    # this cycle's decisions already reached external
                    # truth; the restored scheduler re-runs it as a no-op
                    # (nothing pending is re-decided) — never re-applied
                    sched = _kill_restore(cluster, conf, pipeline,
                                          ckpt_path, c, "post_drain",
                                          restores, corrupt)
                    sched.run_once(now=_VT + c)
                    if pipeline:
                        sched.drain(now=_VT + c)
                if ckpt_path is not None:
                    sched.checkpoint(ckpt_path, now=_VT + c)
                _churn(cluster, c)
        sha = hashlib.sha256(
            repr((applied, _final_state(cluster))).encode()).hexdigest()[:16]
        if tmpdir is not None:
            tmpdir.cleanup()
        return dict(sha=sha, restores=restores, sched=sched, plan=plan,
                    injector=injector)

    clean = run(kill_run=False)

    def outcomes(restores):
        out: Dict[str, int] = {}
        for r in restores:
            out[r["outcome"]] = out.get(r["outcome"], 0) + 1
        return out

    warm0 = METRICS.counter_value("checkpoint_warm_refuse_total")
    kill = run(kill_run=True)
    restore_ms = sorted(r["restore_ms"] for r in kill["restores"])
    # cycles after the LAST restore until the upload path is a delta
    # again (flight isn't checkpointed, so the final scheduler's ring
    # holds exactly the post-restore cycles)
    kinds = [e.get("cycle_kind") for e in kill["sched"].flight.snapshots()]
    cycles_to_steady = next(
        (i for i, k in enumerate(kinds) if k == "delta"), None)
    report: Dict[str, object] = {
        "seed": seed,
        "cycles": cycles,
        "pipeline": pipeline,
        "kills": [[c, p] for c, p in kills],
        "kill_schedule_sha": kill["plan"].schedule_sha(),
        "fault_log": [list(f) for f in kill["injector"].fired],
        "clean_sha": clean["sha"],
        "decisions_sha": kill["sha"],
        "decisions_equal_clean": kill["sha"] == clean["sha"],
        "restores": kill["restores"],
        "restore_outcomes": outcomes(kill["restores"]),
        "restore_ms_p50": (restore_ms[len(restore_ms) // 2]
                           if restore_ms else None),
        "cycles_to_steady": cycles_to_steady,
        "warm_refuses": METRICS.counter_value(
            "checkpoint_warm_refuse_total") - warm0,
    }
    if corrupt_leg:
        fb0 = METRICS.counter_value("checkpoint_restore_total",
                                    {"outcome": "fallback"})
        corrupt = run(kill_run=True, corrupt=True)
        report["corrupt"] = {
            "decisions_sha": corrupt["sha"],
            "decisions_equal_clean": corrupt["sha"] == clean["sha"],
            "restore_outcomes": outcomes(corrupt["restores"]),
            "fallbacks_visible": METRICS.counter_value(
                "checkpoint_restore_total",
                {"outcome": "fallback"}) - fb0,
        }
    return report
