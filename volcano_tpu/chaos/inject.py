"""Fault injector + the seam hook the runtime calls.

The production modules expose their failure seams by calling
:func:`seam` at the exact points a real deployment can break:

======================  ====================================================
seam point              caller
======================  ====================================================
``scheduler.cycle``     Scheduler.run_once (cycle start; arms this cycle's
                        faults)
``session.dispatch``    Session.dispatch_allocate, right before the
                        compiled dispatch (backend loss / slow dispatch)
``delta.run``           ops/fused_io.DeltaKernel.run, before any state is
                        touched (resident-buffer corruption)
``session.complete``    Session.complete_allocate, after the readback and
                        before the integrity compare (mirror drift — a
                        PRE-dispatch drift is invisible: the value diff
                        self-heals it)
``sidecar.complete``    SchedulerSidecar, same point on the served path
``cluster.bind``        FakeCluster.bind (bind dispatch failure)
``cluster.evict``       FakeCluster.evict (evict dispatch failure)
``leader.tick``         runtime/leader.LeaderElector.tick (lease expiry)
``sidecar.round``       SchedulerSidecar serving entry (arms faults per
                        served round)
``sidecar.dispatch``    SchedulerSidecar._dispatch_cycle (backend loss /
                        slow dispatch on the served path)
``sidecar.client_send`` SidecarClient, before sending a request frame
                        (partial-frame injection)
``sidecar.client_recv`` SidecarClient, before reading the response
                        (socket drop after the request landed)
``harness.kill``        chaos/restart.py at each process-kill phase
                        (pre-dispatch / in-flight / post-drain): the
                        harness polls for an armed ``process_kill`` fault
                        — the kill itself is performed by the harness
                        (tear down + checkpoint restore), since a real
                        SIGKILL is not an exception the runtime's
                        fail-soft handlers could be allowed to swallow
``harness.failover``    chaos/failover.py at each kill phase: the harness
                        polls for an armed ``leader_kill`` or
                        ``split_brain`` fault and performs the leader
                        death / deposed-leader write replay itself (same
                        rationale as ``harness.kill``)
``replication.send``    runtime/replication.ReplicationLink.deliver
                        (``replication_partition`` drops the envelope)
``fleet.cycle``         fleet/scheduler.FleetScheduler.run_once (cycle
                        start; arms this fleet cycle's faults)
``fleet.tenant``        fleet/pool.TenantPool.run_bucket, inside ONE
                        tenant's pack step — per-tenant faults
                        (``resident_corrupt`` of that tenant's stacked
                        device row, targeted ``backend_loss``) fire here,
                        scoped by the injector's ``target_tenant`` so the
                        isolation tests can prove a fault in one tenant
                        never moves another tenant's decisions
``fleet.dispatch``      fleet/pool.TenantPool.run_bucket, before the one
                        batched dispatch (whole-bucket backend loss /
                        slow dispatch; skipped when ``target_tenant``
                        scopes the plan to a single tenant)
======================  ====================================================

With no injector installed every seam is a module-global ``None`` check —
zero allocations, no imports, nothing measurable on the hot path.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .plan import Fault, FaultPlan

#: the three distinct points a process death is injected at, relative to
#: the cycle the kill is scheduled in; a process_kill fault's ``param``
#: picks one (param % 3)
KILL_PHASES = ("pre_dispatch", "in_flight", "post_drain")


class ChaosError(RuntimeError):
    """An injected fault surfacing as an exception (e.g. backend loss).

    ``device_ids`` is the attribution contract with the device-health
    registry (parallel/health.py): persistent device faults name the
    devices the failure is pinned to, transient faults leave it empty —
    which is exactly how the registry tells a quarantine-worthy loss from
    a ``backend_loss`` blip the sync-retry rung absorbs."""

    def __init__(self, message: str, kind: str = "chaos",
                 device_ids: Tuple[int, ...] = ()):
        super().__init__(message)
        self.kind = kind
        self.device_ids = tuple(device_ids)


_ACTIVE: Optional["FaultInjector"] = None
_LOCK = threading.Lock()


def active() -> Optional["FaultInjector"]:
    return _ACTIVE


def seam(point: str, **ctx):
    """The hook the runtime calls at each failure seam. No-op (one global
    read) unless an injector is installed."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.on(point, **ctx)


def install(injector: "FaultInjector") -> "FaultInjector":
    global _ACTIVE
    with _LOCK:
        _ACTIVE = injector
    return injector


def uninstall() -> None:
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


@contextlib.contextmanager
def chaos(plan_or_injector):
    """``with chaos(FaultPlan(seed=7)): run()`` — install for the scope."""
    inj = (plan_or_injector if isinstance(plan_or_injector, FaultInjector)
           else FaultInjector(plan_or_injector))
    install(inj)
    try:
        yield inj
    finally:
        uninstall()


class FaultInjector:
    """Fires a :class:`FaultPlan`'s faults at the runtime's seams.

    Faults are released into an armed pool when their scheduled cycle
    begins and fire at the FIRST reachable seam from then on (a
    resident-state fault scheduled before the mirror exists waits,
    deterministically, for the next cycle that has one). ``fired`` is the
    replayable log: (cycle, kind, point) triples in firing order — two
    runs of the same plan over the same workload must produce identical
    logs, which tests/test_chaos.py pins.
    """

    def __init__(self, plan: FaultPlan, slow_s: float = 0.25,
                 target_tenant: Optional[str] = None,
                 heal_after: Optional[int] = None):
        self.plan = plan
        #: how long a ``slow_dispatch`` fault stalls (must exceed the
        #: scheduler's cycle deadline for the watchdog to trip)
        self.slow_s = slow_s
        #: cycles until a dead device comes back (None = never): the
        #: meshloss probe's regrow leg needs the hardware to actually
        #: return; a ``device_flap`` victim re-dies every time a serving
        #: mesh readmits it after healing
        self.heal_after = heal_after
        #: device id -> {"since", "flap", "heal_at"} — devices that are
        #: DEAD RIGHT NOW: every sharded dispatch whose mesh contains one
        #: raises, persistently, until the device heals
        self.dead_devices = {}
        #: healed flap victims, waiting to kill their next serving mesh
        self.flappers = set()
        #: fleet scoping (ISSUE 12): when set, per-tenant fleet faults
        #: fire ONLY inside this tenant's pack step, and whole-bucket
        #: fleet.dispatch faults are suppressed — the chaos isolation
        #: tests inject into one tenant and require every other tenant's
        #: decision stream to stay bit-identical to the clean run
        self.target_tenant = target_tenant
        self.cycle = -1
        self.fired: List[Tuple[int, str, str]] = []
        self._pool: List[Fault] = []
        self._released = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ plumbing
    def begin_cycle(self, cycle: int) -> None:
        """Release every fault scheduled at or before ``cycle``."""
        with self._lock:
            self.cycle = max(self.cycle, int(cycle))
            while (self._released < len(self.plan.faults)
                   and self.plan.faults[self._released].cycle <= self.cycle):
                self._pool.append(self.plan.faults[self._released])
                self._released += 1

    def _take(self, kind: str, point: str) -> Optional[Fault]:
        with self._lock:
            for f in self._pool:
                if f.kind == kind:
                    self._pool.remove(f)
                    self.fired.append((self.cycle, kind, point))
                    return f
        return None

    def pending(self) -> List[Fault]:
        with self._lock:
            return list(self._pool)

    def on(self, point: str, **ctx):
        handler = getattr(self, "_on_" + point.replace(".", "_"), None)
        return handler(**ctx) if handler else None

    # ------------------------------------------------------- seam handlers
    def _on_scheduler_cycle(self, cycle: int, **_):
        self.begin_cycle(cycle)

    def _on_sidecar_round(self, round: int, **_):
        self.begin_cycle(round)

    def _dispatch_faults(self, point: str):
        f = self._take("backend_loss", point)
        if f is not None:
            raise ChaosError("injected backend loss (accelerator gone)",
                             kind="backend_loss")
        f = self._take("slow_dispatch", point)
        if f is not None:
            time.sleep(self.slow_s)

    def _on_session_dispatch(self, session=None, **_):
        self._device_faults("session.dispatch", session)
        self._dispatch_faults("session.dispatch")

    def _device_faults(self, point: str, session) -> None:
        """Persistent device loss on the serving mesh. Unlike every other
        dispatch fault this is NOT one-shot: once a ``device_loss`` or
        ``device_flap`` fault marks a device dead, EVERY later sharded
        dispatch whose mesh still contains it raises with the device
        attributed — the semantics the elastic-mesh rung exists for. The
        raise stops only when the mesh stops including the device (the
        health registry quarantined it and the mesh shrank) or the device
        heals (``heal_after``)."""
        if session is None:
            return
        try:
            mesh = session._sharding_mesh()
        except Exception:
            return
        if mesh is None:
            return
        ids = [int(d.id) for d in mesh.devices.ravel()]
        # heal pass: a revived flap victim moves to the flapper pool
        for dev, rec in list(self.dead_devices.items()):
            if rec["heal_at"] is not None and self.cycle >= rec["heal_at"]:
                del self.dead_devices[dev]
                if rec["flap"]:
                    self.flappers.add(dev)
        f = self._take("device_loss", point)
        if f is not None:
            victim = ids[f.param % len(ids)]
            self.dead_devices[victim] = {
                "since": self.cycle, "flap": False,
                "heal_at": (self.cycle + self.heal_after
                            if self.heal_after else None)}
        f = self._take("device_flap", point)
        if f is not None:
            victim = ids[f.param % len(ids)]
            self.dead_devices[victim] = {
                "since": self.cycle, "flap": True,
                "heal_at": self.cycle + (self.heal_after or 2)}
        # a flapper dies again the moment a serving mesh readmits it
        for dev in ids:
            if dev in self.flappers and dev not in self.dead_devices:
                self.flappers.discard(dev)
                self.dead_devices[dev] = {
                    "since": self.cycle, "flap": True,
                    "heal_at": self.cycle + (self.heal_after or 2)}
                self.fired.append((self.cycle, "device_flap",
                                   point + ":refail"))
        dead = sorted(d for d in ids if d in self.dead_devices)
        if dead:
            flap = any(self.dead_devices[d]["flap"] for d in dead)
            raise ChaosError(
                f"injected device loss: devices {dead} unreachable",
                kind="device_flap" if flap else "device_loss",
                device_ids=tuple(dead))

    def _on_sidecar_dispatch(self, **_):
        self._dispatch_faults("sidecar.dispatch")

    def _on_delta_run(self, kernel=None, state=None, **_):
        if state is None or state.mirror is None:
            return  # nothing resident yet: the fault stays armed
        f = self._take("resident_corrupt", "delta.run")
        if f is not None and state.device is not None:
            import jax
            corrupted = tuple(np.array(b, copy=True) for b in state.mirror)
            _flip_host(corrupted, f.param)
            # the live handles are drained (depth-1 contract: the seam
            # fires before the next dispatch), so dropping them is safe
            if kernel is not None:
                kernel._invalidate(state.device)
            state.device = tuple(jax.device_put(b) for b in corrupted)

    def _drift_mirror(self, point: str, state) -> None:
        # fires AFTER dispatch, before the integrity compare: the mirror
        # diverges from device truth (the self-healing value diff makes a
        # PRE-dispatch drift invisible — it rewrites any drifted element
        # with source truth — so the detectable desync is post-dispatch)
        if state is None or state.mirror is None:
            return
        f = self._take("mirror_drift", point)
        if f is not None:
            _flip_host(state.mirror, f.param)

    def _on_session_complete(self, state=None, **_):
        self._drift_mirror("session.complete", state)

    def _on_sidecar_complete(self, state=None, **_):
        self._drift_mirror("sidecar.complete", state)

    def _on_cluster_bind(self, intent=None, **_):
        if self._take("bind_fail", "cluster.bind") is not None:
            return "fail"

    def _on_cluster_evict(self, intent=None, **_):
        if self._take("evict_fail", "cluster.evict") is not None:
            return "fail"

    def _on_leader_tick(self, elector=None, lease=None, **_):
        f = self._take("lease_expiry", "leader.tick")
        if f is not None and lease is not None and elector is not None:
            # a rival steals the lease and never renews: the elector must
            # step down now and re-acquire after the rival's lease expires
            now = elector.clock()
            lease.holder = "chaos-rival"
            lease.acquire_time = now
            lease.renew_time = now
            lease.transitions += 1
            # every holder transition bumps the fencing token (ISSUE 11):
            # the rival's tenure deposes the elector's generation, so a
            # re-acquisition after expiry wins a HIGHER one
            lease.generation += 1

    def _on_sidecar_client_send(self, client=None, frame: bytes = b"", **_):
        f = self._take("partial_frame", "sidecar.client_send")
        if f is not None and client is not None:
            try:
                client.sock.sendall(frame[:max(1, len(frame) // 2)])
            except OSError:
                pass
            client.sock.close()
            raise ConnectionResetError("chaos: partial frame, socket died "
                                       "mid-send")

    def _on_harness_kill(self, phase: Optional[str] = None, **_):
        """Consume an armed ``process_kill`` fault whose param selects
        ``phase``. Returns the Fault (the harness then performs the kill:
        discard the process's runtime objects and restore from the
        checkpoint) or None. Only the restart harness calls this seam —
        the production runtime cannot inject its own death."""
        with self._lock:
            for f in self._pool:
                if f.kind == "process_kill" \
                        and KILL_PHASES[f.param % len(KILL_PHASES)] == phase:
                    self._pool.remove(f)
                    self.fired.append((self.cycle, "process_kill",
                                       f"harness.kill:{phase}"))
                    return f
        return None

    def _on_harness_failover(self, kind: Optional[str] = None,
                             phase: Optional[str] = None, **_):
        """Consume an armed ``leader_kill`` or ``split_brain`` fault whose
        param selects ``phase``. Returns the Fault (the failover harness
        then performs the leader death / the deposed leader's write
        replay) or None. Only chaos/failover.py calls this seam — the
        production runtime cannot inject its own death."""
        with self._lock:
            for f in self._pool:
                if f.kind == kind \
                        and KILL_PHASES[f.param % len(KILL_PHASES)] == phase:
                    self._pool.remove(f)
                    self.fired.append((self.cycle, kind,
                                       f"harness.failover:{phase}"))
                    return f
        return None

    def _on_replication_send(self, envelope=None, link=None, **_):
        if self._take("replication_partition",
                      "replication.send") is not None:
            return "drop"

    # ------------------------------------------------- fleet seam handlers
    def _on_fleet_cycle(self, cycle: int = 0, **_):
        self.begin_cycle(cycle)

    def _on_fleet_tenant(self, pool=None, bucket=None, tenant=None,
                         resident=None, **_):
        if self.target_tenant is not None and tenant != self.target_tenant:
            return
        if (bucket is not None and bucket.device is not None
                and tenant in bucket.stacked_names):
            f = self._take("resident_corrupt", "fleet.tenant")
            if f is not None:
                # corrupt ONE element of THIS tenant's row of the stacked
                # device residency, behind the pool's back: the tenant's
                # in-graph digest trips at the next dispatch and the
                # bucket recovers by a full re-stack from source truth —
                # decision-neutral for every tenant (the flat kernel's
                # recovery argument, per row)
                import jax

                from ..fleet.pool import _invalidate
                r = bucket.stacked_names.index(tenant)
                host = [np.array(b, copy=True) for b in bucket.device]
                _flip_host(tuple(h[r] for h in host), f.param)
                _invalidate(bucket.device)
                bucket.device = tuple(jax.device_put(h) for h in host)
                # one fault per seam visit: a backend loss in the SAME
                # pack step would exclude this tenant from the batch and
                # the structural restack would wipe the corruption before
                # any digest verify ran — the loss stays armed for the
                # next reachable seam instead
                return
        f = self._take("backend_loss", "fleet.tenant")
        if f is not None:
            # surfaces inside the tenant's pack step: run_bucket excludes
            # ONLY this tenant from the batch and the caller serves it
            # through the per-tenant fallback ladder
            raise ChaosError("injected backend loss (tenant pack)",
                             kind="backend_loss")

    def _on_fleet_dispatch(self, pool=None, bucket=None, tenants=(), **_):
        if self.target_tenant is not None:
            return  # targeted plans never fault the whole bucket
        self._dispatch_faults("fleet.dispatch")

    def _on_sidecar_client_recv(self, client=None, **_):
        f = self._take("socket_drop", "sidecar.client_recv")
        if f is not None and client is not None:
            client.sock.close()
            raise ConnectionResetError("chaos: socket dropped before the "
                                       "response was read")


def _flip_host(bufs, param: int) -> None:
    """Flip one element of one non-empty host group buffer, chosen by
    ``param``. The flip is guaranteed to CHANGE the value: bools invert,
    f32/i32 get a bit-level xor (a NaN-producing flip is fine — the
    value diff treats NaN as always-changed and the digest is bit-level)."""
    nonempty = [b for b in bufs if b.size]
    if not nonempty:
        return
    buf = nonempty[param % len(nonempty)]
    i = param % buf.size
    if buf.dtype == np.bool_:
        buf[i] = not buf[i]
    else:
        view = buf.view(np.uint32)
        view[i] = view[i] ^ np.uint32(0x5A5A5A5A)
