"""Self-contained chaos probe: a seeded fault storm over a multi-cycle
scheduler run, compared against the identical no-fault run.

Shared by the tier-1 smoke (``python -m volcano_tpu.chaos --smoke``) and
bench.py's ``robustness`` block. The probe is the executable form of the
fail-soft claim: under every recoverable fault kind the loop keeps serving
and its decision sha stays bit-identical to the clean run, a planted
resident-state corruption provably trips the integrity digest, and the
recovery shows up in the flight-recorder ring.
"""

from __future__ import annotations

import contextlib
import hashlib
from typing import Dict, Optional

from .inject import FaultInjector, chaos
from .plan import RECOVERABLE_KINDS, FaultPlan

#: allocate-terminal policy so the pipelined loop can defer the readback
#: (the same shape tests/test_delta_pipeline.py pins)
_PROBE_CONF = """
actions: "enqueue, allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: proportion
  - name: binpack
"""


def _small_cluster(n_nodes: int = 6, n_jobs: int = 8,
                   tasks_per_job: int = 3):
    from ..api import (ClusterInfo, JobInfo, NodeInfo, PodGroupPhase,
                       QueueInfo, Resource, TaskInfo)
    ci = ClusterInfo()
    for i in range(n_nodes):
        ci.add_node(NodeInfo(
            f"n{i}", allocatable=Resource.from_resource_list(
                {"cpu": "8", "memory": "16Gi", "pods": "110"})))
    ci.add_queue(QueueInfo("default", weight=1))
    for j in range(n_jobs):
        job = JobInfo(uid=f"default/j{j}", name=f"j{j}",
                      namespace="default", queue="default", min_available=2,
                      priority=j % 3, creation_timestamp=float(j),
                      pod_group_phase=PodGroupPhase.INQUEUE)
        for t in range(tasks_per_job):
            job.add_task(TaskInfo(
                uid=f"default/j{j}-t{t}", name=f"j{j}-t{t}",
                namespace="default",
                resreq=Resource.from_resource_list(
                    {"cpu": "2", "memory": "2Gi"})))
        ci.add_job(job)
    return ci


def _churn(cluster, cycle: int) -> None:
    """Deterministic between-cycle churn: bound tasks start running, one
    fully-running gang completes and re-arrives."""
    from ..api import TaskStatus
    ci = cluster.ci
    for uid in sorted(t.uid for job in ci.jobs.values()
                      for t in job.tasks.values()
                      if t.status == TaskStatus.BOUND):
        cluster.run_task(uid)
    for uid in sorted(ci.jobs):
        job = ci.jobs[uid]
        tasks = list(job.tasks.values())
        if tasks and all(t.status == TaskStatus.RUNNING for t in tasks) \
                and (cycle + len(uid)) % 3 == 0:
            for t in tasks:
                node = ci.nodes.get(t.node_name)
                if node is not None and t.uid in node.tasks:
                    node.remove_task(t)
                    cluster.mark_dirty(node_name=node.name)
                job.update_task_status(t, TaskStatus.PENDING)
                t.node_name = ""
            job.allocated = type(job.allocated)({})
            cluster.mark_dirty(job_uid=uid)
            break


def _cycle_digest(rec) -> tuple:
    return (sorted((b.task_uid, b.node_name, b.gpu_index)
                   for b in rec.binds),
            sorted(e.task_uid for e in rec.evictions),
            sorted(rec.pipelined.items()),
            sorted((u, str(p)) for u, p in rec.phase_updates.items()))


def run_chaos_probe(seed: int = 7, cycles: int = 8, pipeline: bool = True,
                    kinds=RECOVERABLE_KINDS,
                    deadline_ms: Optional[float] = None,
                    slow_s: float = 0.25,
                    sharding: bool = False,
                    use_pallas: Optional[str] = None,
                    wave_width: Optional[int] = None) -> Dict[str, object]:
    """Run the probe; returns a JSON-ready robustness report.

    ``sharding`` runs both the clean and the fault runs on the node-axis
    sharded backend (conf ``sharding: true``): fault recovery and the
    per-shard digest discipline must hold there exactly as on the
    single-device path. ``use_pallas`` ("interpret" in CI) selects the
    kernel path via the same conf knob — combined with ``sharding`` it
    puts the storm on the shard-local pallas candidate launch
    (ISSUE 14). ``wave_width`` (> 1) runs the storm on the wavefront
    placement path (ISSUE 16, conf ``wave_width: W``): faults land
    mid-wave, and the order-preserving commit rule must keep the fault
    run's decisions bit-identical to the clean run anyway."""
    from ..framework.conf import parse_conf
    from ..metrics import METRICS
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler
    conf = parse_conf(("sharding: true\n" if sharding else "")
                      + (f"use_pallas: {use_pallas}\n" if use_pallas else "")
                      + (f"wave_width: {int(wave_width)}\n"
                         if wave_width else "")
                      + _PROBE_CONF)
    base = _small_cluster()

    def run(injector):
        cluster = FakeCluster(base.clone())
        sched = Scheduler(cluster, conf=conf, pipeline=pipeline)
        if deadline_ms is not None:
            sched.cycle_deadline_s = deadline_ms / 1000.0
        digests = []
        ctx = chaos(injector) if injector is not None \
            else contextlib.nullcontext()
        from ..runtime.driver import step_cycle
        with ctx:
            for c in range(cycles):
                rec = step_cycle(sched, now=1000.0 + c)
                digests.append(_cycle_digest(rec))
                _churn(cluster, c)
        sha = hashlib.sha256(repr(digests).encode()).hexdigest()[:16]
        return sha, sched

    clean_sha, _clean = run(None)
    plan = FaultPlan(seed=seed, cycles=cycles, kinds=kinds)
    injector = FaultInjector(plan, slow_s=slow_s)
    mismatches0 = METRICS.counter_value("resident_digest_mismatch_total")
    recoveries0 = METRICS.counter_total("cycle_recoveries_total")
    chaos_sha, sched = run(injector)
    flight = sched.flight.snapshots()
    recovery_ms = sorted(e["stats"]["recovery_ms"] for e in flight
                         if "recovery_ms" in e.get("stats", {}))
    degradation = [e.get("degradation", 0) or 0 for e in flight]
    return {
        "seed": seed,
        "cycles": cycles,
        "pipeline": pipeline,
        "sharding": sharding,
        "use_pallas": use_pallas,
        "wave_width": wave_width,
        "mesh_devices": next(
            (int(e["mesh_devices"]) for e in reversed(flight)
             if e.get("mesh_devices") is not None), None),
        "kinds": list(kinds),
        "fault_schedule_sha": plan.schedule_sha(),
        "faults_fired": len(injector.fired),
        "fault_log": [list(f) for f in injector.fired],
        "decisions_sha": chaos_sha,
        "clean_sha": clean_sha,
        "decisions_equal_clean": chaos_sha == clean_sha,
        "recovered_cycles": len(recovery_ms),
        "recovery_ms_p50": (recovery_ms[len(recovery_ms) // 2]
                            if recovery_ms else None),
        "degradation_max": max(degradation) if degradation else 0,
        "digest_mismatches": METRICS.counter_value(
            "resident_digest_mismatch_total") - mismatches0,
        "recoveries_total": METRICS.counter_total(
            "cycle_recoveries_total") - recoveries0,
        "resync_dead_letter": len(sched.resync.dead_letter()),
    }
