"""Job controller: reconciles batch Jobs into pods + a PodGroup.

Reference: pkg/controllers/job/ (3,546 LoC) — event handlers mapping
pod/job/command events to Requests (job_controller_handler.go:40-436), the
per-state Execute through the state machine (state/*.go), syncJob creating
and deleting pods to match task replicas with the PodGroup-phase gate
(job_controller_actions.go:200-444), killJob (46-150), PodGroup
create/update with calcPGMinResources (533-676), PVC creation (445-532),
maxRetry handling (job_controller.go:324-337), and the fork's counter-label
numbering (job_controller_actions.go:266-324).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional

from ..api.batch import Job, TaskSpec
from ..api.core import (JOB_NAME_LABEL, POD_GROUP_ANNOTATION,
                        TASK_SPEC_ANNOTATION, Pod, PodGroup, PodPhase)
from ..api.resource import Resource
from ..api.types import BusAction, BusEvent, JobPhase, PodGroupPhase
from .framework import Controller, register_controller
from .job_plugins import get_job_plugin
from .job_state import (ACTIVE_PHASES, TERMINAL_PHASES, Request,
                        apply_policies, next_phase_for_action)

#: fork feature: annotation enabling monotonically numbered pod labels
#: (job_controller_actions.go:266-324)
COUNTER_LABEL_ANNOTATION = "volcano.sh/counter-label"


class JobController(Controller):
    name = "job-controller"

    def initialize(self, apiserver) -> None:
        self.api = apiserver
        self.queue: Deque[Request] = deque()
        self._counter: Dict[str, int] = {}   # job key -> next counter label
        # controller-local pod phase cache: objects in the store are mutated
        # in place, so phase *transitions* are derived from this last-seen
        # view (the role of pkg/controllers/cache, cache.go:1-325)
        self._pod_phase: Dict[str, str] = {}
        apiserver.watch("jobs", self._on_job_event)
        apiserver.watch("pods", self._on_pod_event)
        apiserver.watch("commands", self._on_command_event)
        apiserver.watch("podgroups", self._on_podgroup_event)

    # ------------------------------------------------------- event handlers
    def _on_job_event(self, event, job: Job, old) -> None:
        if event == "deleted":
            self._cleanup_job(job)
            return
        self.queue.append(Request(job.key, event=BusEvent.OUT_OF_SYNC))

    def _on_pod_event(self, event, pod: Pod, old) -> None:
        job_name = pod.job_name
        if not job_name:
            return
        key = f"{pod.namespace}/{job_name}"
        if event == "deleted":
            self._pod_phase.pop(pod.key, None)
            self.queue.append(Request(key, event=BusEvent.OUT_OF_SYNC))
            return
        prev = self._pod_phase.get(pod.key)
        self._pod_phase[pod.key] = pod.phase
        if prev is not None and prev != pod.phase:
            if pod.phase == PodPhase.FAILED:
                ev = (BusEvent.POD_EVICTED if pod.deletion_timestamp
                      else BusEvent.POD_FAILED)
                self.queue.append(Request(key, event=ev,
                                          task_role=pod.task_role,
                                          exit_code=pod.exit_code))
                return
            if pod.phase == PodPhase.SUCCEEDED:
                if self._task_completed(key, pod.task_role):
                    self.queue.append(Request(key,
                                              event=BusEvent.TASK_COMPLETED,
                                              task_role=pod.task_role))
                    return
        self.queue.append(Request(key, event=BusEvent.OUT_OF_SYNC))

    def _on_command_event(self, event, cmd, old) -> None:
        """Bus commands become explicit-action requests; the Command object
        is consumed (job_controller_handler.go:40 + handleCommands:364)."""
        if event != "added" or cmd.target_kind != "Job":
            return
        self.api.delete("commands", self.api._key(cmd))
        self.queue.append(Request(f"{cmd.namespace}/{cmd.target_name}",
                                  event=BusEvent.COMMAND_ISSUED,
                                  action=cmd.action))

    def _on_podgroup_event(self, event, pg: PodGroup, old) -> None:
        if pg.owner_job and event == "updated":
            self.queue.append(Request(pg.owner_job, event=BusEvent.OUT_OF_SYNC))

    def _task_completed(self, job_key: str, role: str) -> bool:
        """All replicas of the role succeeded (controllers/cache TaskCompleted)."""
        job = self.api.get("jobs", job_key)
        if job is None:
            return False
        spec = next((t for t in job.tasks if t.name == role), None)
        if spec is None:
            return False
        pods = [p for p in self.api.pods_of_job(job_key)
                if p.task_role == role]
        return (len([p for p in pods if p.phase == PodPhase.SUCCEEDED])
                >= spec.replicas)

    # ------------------------------------------------------------ reconcile
    def process_all(self, max_items: int = 10000) -> None:
        for _ in range(max_items):
            if not self.queue:
                return
            req = self.queue.popleft()
            self.process(req)

    def process(self, req: Request) -> None:
        job = self.api.get("jobs", req.job_key)
        if job is None:
            return
        action = apply_policies(job, req)
        phase = job.status.state.phase

        if action == BusAction.RESTART_JOB and phase in ACTIVE_PHASES:
            if job.status.retry_count >= job.max_retry:
                # retries exhausted -> job fails (job_controller.go:324-337)
                self._kill_job(job, JobPhase.FAILED,
                               reason="retries exhausted")
                return
            job.status.retry_count += 1

        target = next_phase_for_action(phase, action)
        if target is not None:
            if target == JobPhase.PENDING:   # ResumeJob
                self._set_phase(job, JobPhase.PENDING, reason="resumed")
                self._sync_job(job)
            elif target == JobPhase.RESTARTING:
                # restart deletes everything incl. Failed pods so sync can
                # recreate them (PodRetainPhaseNone, state/restarting.go)
                self._kill_job(job, JobPhase.RESTARTING,
                               reason=str(action.value), retain=False)
            else:
                final = {JobPhase.ABORTING: JobPhase.ABORTED,
                         JobPhase.TERMINATING: JobPhase.TERMINATED,
                         JobPhase.COMPLETING: JobPhase.COMPLETED}
                self._kill_job(job, target, reason=str(action.value),
                               final_phase=final.get(target))
            return

        if phase in TERMINAL_PHASES:
            return
        self._sync_job(job)

    # -------------------------------------------------------------- syncJob
    def _sync_job(self, job: Job) -> None:
        """Create/delete pods to match spec; manage PodGroup; update status
        (job_controller_actions.go:200-444)."""
        if job.status.state.phase == JobPhase.RESTARTING:
            # wait for old pods to disappear, then recreate
            if self.api.pods_of_job(job.key):
                return
            self._set_phase(job, JobPhase.PENDING, reason="restarting done")

        self._ensure_job_initialized(job)
        pg = self._ensure_podgroup(job)

        pods = self.api.pods_of_job(job.key)
        by_role: Dict[str, List[Pod]] = {}
        for p in pods:
            by_role.setdefault(p.task_role, []).append(p)

        # pod creation is gated on the PodGroup leaving Pending
        # (syncTask gate, job_controller_actions.go:224-231)
        may_create = pg.phase != PodGroupPhase.PENDING
        for task in job.tasks:
            have = by_role.get(task.name, [])
            have_names = {p.name for p in have}
            # scale down: delete the highest-index extras first
            want_names = [self._pod_name(job, task, i)
                          for i in range(task.replicas)]
            for p in have:
                if p.name not in want_names:
                    self._delete_pod(p)
            if may_create:
                for i, pname in enumerate(want_names):
                    if pname not in have_names:
                        self._create_pod(job, task, i)

        self._update_status(job)

    def _ensure_job_initialized(self, job: Job) -> None:
        """First reconcile: plugins + PVCs (initiateJob,
        job_controller_actions.go:151-199 + 445-532)."""
        if job.status.controlled_resources.get("initialized"):
            return
        for plugin_name in job.plugins:
            get_job_plugin(plugin_name).on_job_add(job, self.api)
        for i, vol in enumerate(job.volumes):
            if not vol.volume_claim_name and vol.storage:
                vol.volume_claim_name = f"{job.name}-pvc-{i}"
            if vol.volume_claim_name and self.api.get(
                    "pvcs", f"{job.namespace}/{vol.volume_claim_name}") is None:
                self.api.create("pvcs", PVC(name=vol.volume_claim_name,
                                            namespace=job.namespace,
                                            storage=vol.storage))
        job.status.controlled_resources["initialized"] = "true"

    def _ensure_podgroup(self, job: Job) -> PodGroup:
        pg = self.api.podgroup_of_job(job.key)
        if pg is None:
            pg = PodGroup(
                name=job.name, namespace=job.namespace, owner_job=job.key,
                min_member=job.min_available, queue=job.queue,
                priority_class_name=job.priority_class_name,
                min_resources=self._calc_pg_min_resources(job))
            self.api.create("podgroups", pg)
        else:
            pg.min_member = job.min_available
            pg.min_resources = self._calc_pg_min_resources(job)
        return pg

    def _calc_pg_min_resources(self, job: Job) -> Dict[str, object]:
        """Sum the first minAvailable pods' requests, tasks ordered by
        priority (calcPGMinResources, job_controller_actions.go:533-676)."""
        total = Resource()
        remaining = job.min_available
        for task in sorted(job.tasks, key=lambda t: -t.template.priority):
            take = min(task.replicas, remaining)
            if take > 0:
                total.add(task.template.resreq().multi(take))
            remaining -= take
            if remaining <= 0:
                break
        out: Dict[str, object] = {}
        for name in total.resource_names():
            v = total.get(name)
            out[name] = v / 1000.0 if name == "cpu" else v
        return out

    def _pod_name(self, job: Job, task: TaskSpec, index: int) -> str:
        return f"{job.name}-{task.name}-{index}"

    def _create_pod(self, job: Job, task: TaskSpec, index: int) -> None:
        tmpl = task.template
        pod = Pod(
            name=self._pod_name(job, task, index), namespace=job.namespace,
            labels={**tmpl.labels, JOB_NAME_LABEL: job.name},
            annotations={**tmpl.annotations,
                         TASK_SPEC_ANNOTATION: task.name,
                         POD_GROUP_ANNOTATION: job.name},
            scheduler_name=job.scheduler_name,
            resources=dict(tmpl.resources),
            node_selector=dict(tmpl.node_selector),
            tolerations=list(tmpl.tolerations),
            affinity_required=list(tmpl.affinity_required),
            affinity_preferred=list(tmpl.affinity_preferred),
            priority=tmpl.priority, restart_policy=tmpl.restart_policy,
            env=dict(tmpl.env), volumes=list(tmpl.volumes))
        # fork's counter-label: monotonically numbered pod label
        if COUNTER_LABEL_ANNOTATION in job.annotations:
            label_key = job.annotations[COUNTER_LABEL_ANNOTATION]
            n = self._counter.get(job.key, 0)
            pod.labels[label_key] = str(n)
            self._counter[job.key] = n + 1
        for plugin_name in job.plugins:
            get_job_plugin(plugin_name).on_pod_create(job, pod, index, self.api)
        self.api.create("pods", pod)

    def _delete_pod(self, pod: Pod) -> None:
        self.api.delete("pods", pod.key)

    # -------------------------------------------------------------- killJob
    def _kill_job(self, job: Job, phase: JobPhase, reason: str = "",
                  final_phase: Optional[JobPhase] = None,
                  retain: bool = True) -> None:
        """Delete the job's pods; enter the -ing phase now and the final
        phase once pods are gone (killJob, job_controller_actions.go:46-150).
        With ``retain`` (PodRetainPhaseSoft) Succeeded/Failed pods survive;
        restarts pass retain=False (PodRetainPhaseNone)."""
        self._set_phase(job, phase, reason)
        for pod in self.api.pods_of_job(job.key):
            if retain and pod.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            self._delete_pod(pod)
        if final_phase is not None:
            self._set_phase(job, final_phase, reason)
        self._update_status(job, transition=False)

    def _cleanup_job(self, job: Job) -> None:
        for pod in self.api.pods_of_job(job.key):
            self._delete_pod(pod)
        pg = self.api.podgroup_of_job(job.key)
        if pg is not None:
            self.api.delete("podgroups", pg.key)
        for plugin_name in job.plugins:
            get_job_plugin(plugin_name).on_job_delete(job, self.api)

    # -------------------------------------------------------------- status
    def _set_phase(self, job: Job, phase: JobPhase, reason: str = "") -> None:
        if job.status.state.phase != phase:
            job.status.state.phase = phase
            job.status.state.reason = reason
            job.status.state.transition_time = time.time()
            job.status.version += 1

    def _update_status(self, job: Job, transition: bool = True) -> None:
        pods = self.api.pods_of_job(job.key)
        s = job.status
        s.pending = sum(1 for p in pods if p.phase == PodPhase.PENDING)
        s.running = sum(1 for p in pods if p.phase == PodPhase.RUNNING)
        s.succeeded = sum(1 for p in pods if p.phase == PodPhase.SUCCEEDED)
        s.failed = sum(1 for p in pods if p.phase == PodPhase.FAILED)
        s.min_available = job.min_available
        s.task_status_count = {}
        for p in pods:
            s.task_status_count.setdefault(p.task_role, {}).setdefault(p.phase, 0)
            s.task_status_count[p.task_role][p.phase] += 1

        if not transition:
            return
        phase = s.state.phase
        total = job.total_replicas()
        if phase == JobPhase.PENDING and s.running >= job.min_available > 0:
            self._set_phase(job, JobPhase.RUNNING, "min available running")
        elif phase in (JobPhase.PENDING, JobPhase.RUNNING):
            min_success = job.min_success or total
            if total > 0 and s.succeeded >= min_success:
                self._set_phase(job, JobPhase.COMPLETED, "job completed")
            elif (total > 0 and s.failed > 0
                    and s.failed > total - job.min_available):
                # minAvailable no longer reachable
                self._set_phase(job, JobPhase.FAILED, "insufficient pods")


from dataclasses import dataclass as _dataclass


@_dataclass
class PVC:
    """PersistentVolumeClaim stand-in created per job volume
    (createJobIOIfNotExist, job_controller_actions.go:445-532)."""

    name: str
    namespace: str = "default"
    storage: str = ""


register_controller(JobController)
