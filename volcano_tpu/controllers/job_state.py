"""Job state machine: phase x action -> behavior.

Reference: pkg/controllers/job/state/ (9 files; factory.go:62-85 state
dispatch, running.go:30-96 and siblings for per-state action handling) and
the policy-resolution order in job_controller_util.go:145-200:
explicit action > OutOfSync > task-level policies (event/exit-code match) >
job-level policies > default SyncJob.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api.batch import Job
from ..api.types import BusAction, BusEvent, JobPhase


@dataclass
class Request:
    """A unit of reconcile work (reference: pkg/controllers/apis/request.go:25-42)."""

    job_key: str
    event: Optional[BusEvent] = None
    action: Optional[BusAction] = None
    task_role: str = ""
    exit_code: Optional[int] = None


def apply_policies(job: Job, req: Request) -> BusAction:
    """Resolve which action to run for a request
    (job_controller_util.go:145-200)."""
    if req.action is not None:
        return req.action
    if req.event == BusEvent.OUT_OF_SYNC:
        return BusAction.SYNC_JOB
    if req.task_role:
        for task in job.tasks:
            if task.name != req.task_role:
                continue
            for policy in task.policies:
                if req.event is not None and policy.matches_event(req.event):
                    return policy.action
                if policy.matches_exit_code(req.exit_code):
                    return policy.action
    for policy in job.policies:
        if req.event is not None and policy.matches_event(req.event):
            return policy.action
        if policy.matches_exit_code(req.exit_code):
            return policy.action
    return BusAction.SYNC_JOB


#: phases in which pods may still run / be created
ACTIVE_PHASES = (JobPhase.PENDING, JobPhase.RUNNING, JobPhase.RESTARTING)
#: terminal phases
TERMINAL_PHASES = (JobPhase.COMPLETED, JobPhase.FAILED, JobPhase.TERMINATED,
                   JobPhase.ABORTED)


def next_phase_for_action(phase: JobPhase, action: BusAction) -> Optional[JobPhase]:
    """The transition each action triggers from a given phase, or None if the
    action is a no-op there (state/{pending,running,aborted,...}.go).

    Kill-type actions first enter an intermediate *-ing phase; the controller
    moves to the final phase once the pods are gone (see JobController._sync).
    """
    if action == BusAction.ABORT_JOB:
        if phase not in (JobPhase.ABORTED, JobPhase.ABORTING):
            return JobPhase.ABORTING
        return None
    if action == BusAction.TERMINATE_JOB:
        if phase not in (JobPhase.TERMINATED, JobPhase.TERMINATING):
            return JobPhase.TERMINATING
        return None
    if action == BusAction.COMPLETE_JOB:
        if phase not in (JobPhase.COMPLETED, JobPhase.COMPLETING):
            return JobPhase.COMPLETING
        return None
    if action == BusAction.RESTART_JOB or action == BusAction.RESTART_TASK:
        if phase in ACTIVE_PHASES:
            return JobPhase.RESTARTING
        return None
    if action == BusAction.RESUME_JOB:
        if phase == JobPhase.ABORTED or phase == JobPhase.ABORTING:
            return JobPhase.PENDING
        return None
    return None  # SyncJob / EnqueueJob handled by the sync path
