"""Queue controller: Open/Closed/Closing state machine + podgroup tallies.

Reference: pkg/controllers/queue/ (1,010 LoC) — bus Commands
OpenQueue/CloseQueue (queue_controller.go:267-331), open/close actions with
live-podgroup checks (queue_controller_action.go:78-170), and aggregation of
podgroup phase counts into QueueStatus (queue_controller_action.go:44-76).
"""

from __future__ import annotations

from collections import deque
from typing import Deque

from ..api.queue_info import QueueInfo
from ..api.types import BusAction, PodGroupPhase, QueueState
from .framework import Controller, register_controller


class QueueController(Controller):
    name = "queue-controller"

    def initialize(self, apiserver) -> None:
        self.api = apiserver
        self.queue: Deque[str] = deque()
        apiserver.watch("queues", self._on_queue)
        apiserver.watch("podgroups", self._on_podgroup)
        apiserver.watch("commands", self._on_command)

    def _on_queue(self, event, queue, old) -> None:
        self.queue.append(queue.name)

    def _on_podgroup(self, event, pg, old) -> None:
        if pg.queue:
            self.queue.append(pg.queue)

    def _on_command(self, event, cmd, old) -> None:
        if event != "added" or cmd.target_kind != "Queue":
            return
        if cmd.action not in (BusAction.OPEN_QUEUE, BusAction.CLOSE_QUEUE):
            return
        self.api.delete("commands", self.api._key(cmd))
        queue = self.api.get("queues", cmd.target_name)
        if queue is None:
            return
        if cmd.action == BusAction.OPEN_QUEUE:
            queue.state = QueueState.OPEN
        else:
            queue.state = (QueueState.CLOSING if self._live_podgroups(queue.name)
                           else QueueState.CLOSED)
        self.queue.append(queue.name)

    def _live_podgroups(self, queue_name: str) -> int:
        return len(self.api.list(
            "podgroups",
            lambda pg: pg.queue == queue_name
            and pg.phase in (PodGroupPhase.PENDING, PodGroupPhase.INQUEUE,
                             PodGroupPhase.RUNNING, PodGroupPhase.UNKNOWN)))

    def process_all(self) -> None:
        seen = set()
        while self.queue:
            name = self.queue.popleft()
            if name in seen:
                continue
            seen.add(name)
            self.sync_queue(name)

    def sync_queue(self, name: str) -> None:
        queue: QueueInfo = self.api.get("queues", name)
        if queue is None:
            return
        # Closing -> Closed once no live podgroups remain
        if queue.state == QueueState.CLOSING and not self._live_podgroups(name):
            queue.state = QueueState.CLOSED
        # tally podgroup phases into annotations (stand-in for QueueStatus)
        counts = {p.value: 0 for p in PodGroupPhase}
        for pg in self.api.list("podgroups", lambda pg: pg.queue == name):
            counts[pg.phase.value] = counts.get(pg.phase.value, 0) + 1
        for phase, n in counts.items():
            queue.annotations[f"status.{phase.lower()}"] = str(n)


register_controller(QueueController)
