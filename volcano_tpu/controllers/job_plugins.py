"""Job plugins: per-pod environment/service injection.

Reference: pkg/controllers/job/plugins/ —
- ``env``: VC_TASK_INDEX / job name env vars (env/env.go:45-83),
- ``svc``: headless service + hosts configmap so gang members resolve each
  other by stable DNS names (svc/svc.go:76-353),
- ``ssh``: per-job keypair secret mounted as authorized_keys so MPI-style
  launchers can fan out (ssh/ssh.go:64-238). Key material here is random
  placeholder bytes — the contract (secret exists, pods reference it) is what
  the controllers and tests exercise, not real crypto.

Interface mirrors PluginInterface{OnPodCreate,OnJobAdd,OnJobDelete}
(plugins/interface/interface.go:29-50).
"""

from __future__ import annotations

import secrets as _secrets
from dataclasses import dataclass, field
from typing import Dict, List

from ..api.batch import Job
from ..api.core import Pod


@dataclass
class SecretObject:
    name: str
    namespace: str = "default"
    data: Dict[str, str] = field(default_factory=dict)


class NetworkPolicyObject:
    """Job-scoped ingress isolation (svc.go:316-353): only pods of the
    same job may talk to the job's pods."""

    def __init__(self, name, namespace, pod_selector, ingress_from):
        self.name = name
        self.namespace = namespace
        self.pod_selector = dict(pod_selector)
        self.ingress_from = dict(ingress_from)
        self.policy_types = ["Ingress"]


@dataclass
class ServiceObject:
    name: str
    namespace: str = "default"
    headless: bool = True
    selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class ConfigMapObject:
    name: str
    namespace: str = "default"
    data: Dict[str, str] = field(default_factory=dict)


class JobPlugin:
    name = ""

    def on_job_add(self, job: Job, apiserver) -> None:
        pass

    def on_pod_create(self, job: Job, pod: Pod, index: int, apiserver) -> None:
        pass

    def on_job_delete(self, job: Job, apiserver) -> None:
        pass


class EnvPlugin(JobPlugin):
    name = "env"

    def on_pod_create(self, job, pod, index, apiserver):
        pod.env["VC_TASK_INDEX"] = str(index)
        pod.env["VK_TASK_INDEX"] = str(index)   # legacy name kept by reference
        pod.env["VC_JOB_NAME"] = job.name


class SvcPlugin(JobPlugin):
    name = "svc"

    def _hosts(self, job: Job) -> Dict[str, str]:
        """All-hosts file plus one ``<task>.host`` file per role, the files
        MPI/TF launch commands read from /etc/volcano (reference svc plugin
        configmap, svc.go:76-200; e.g. mpiworker.host in e2e mpi.go)."""
        lines: List[str] = []
        data: Dict[str, str] = {}
        for task in job.tasks:
            task_lines = [f"{job.name}-{task.name}-{i}.{job.name}"
                          for i in range(task.replicas)]
            data[f"{task.name}.host"] = "\n".join(task_lines)
            lines.extend(task_lines)
        data["hosts"] = "\n".join(lines)
        return data

    def on_job_add(self, job, apiserver):
        svc = ServiceObject(name=job.name, namespace=job.namespace,
                            selector={"volcano.sh/job-name": job.name})
        cm = ConfigMapObject(name=f"{job.name}-svc", namespace=job.namespace,
                             data=self._hosts(job))
        if apiserver.get("services", f"{job.namespace}/{job.name}") is None:
            apiserver.create("services", svc)
        if apiserver.get("configmaps", f"{job.namespace}/{job.name}-svc") is None:
            apiserver.create("configmaps", cm)
        # job-scoped NetworkPolicy unless disabled by the plugin argument
        # (svc.go:48-69 disable-network-policy flag + :144-146 creation)
        args = job.plugins.get(self.name, []) or []
        if "--disable-network-policy=true" not in args \
                and "--disable-network-policy" not in args:
            key = f"{job.namespace}/{job.name}"
            if apiserver.get("networkpolicies", key) is None:
                sel = {"volcano.sh/job-name": job.name,
                       "volcano.sh/job-namespace": job.namespace}
                apiserver.create("networkpolicies", NetworkPolicyObject(
                    name=job.name, namespace=job.namespace,
                    pod_selector=sel, ingress_from=sel))
        job.status.controlled_resources["plugin-svc"] = job.name

    def on_pod_create(self, job, pod, index, apiserver):
        hosts = []
        for task in job.tasks:
            names = ",".join(f"{job.name}-{task.name}-{i}.{job.name}"
                             for i in range(task.replicas))
            pod.env[f"VC_{task.name.upper().replace('-', '_')}_HOSTS"] = names
            hosts.append(names)
        pod.env["VC_JOB_HOSTS"] = ";".join(hosts)

    def on_job_delete(self, job, apiserver):
        apiserver.delete("services", f"{job.namespace}/{job.name}")
        apiserver.delete("configmaps", f"{job.namespace}/{job.name}-svc")
        apiserver.delete("networkpolicies", f"{job.namespace}/{job.name}")


class SSHPlugin(JobPlugin):
    name = "ssh"

    def on_job_add(self, job, apiserver):
        key = f"{job.namespace}/{job.name}-ssh"
        if apiserver.get("secrets", key) is None:
            private = _secrets.token_hex(32)
            public = _secrets.token_hex(16)
            apiserver.create("secrets", SecretObject(
                name=f"{job.name}-ssh", namespace=job.namespace,
                data={"id_rsa": private, "id_rsa.pub": public,
                      "authorized_keys": public,
                      "config": "StrictHostKeyChecking no\n"}))
        job.status.controlled_resources["plugin-ssh"] = f"{job.name}-ssh"

    def on_pod_create(self, job, pod, index, apiserver):
        pod.volumes.append(f"{job.name}-ssh")

    def on_job_delete(self, job, apiserver):
        apiserver.delete("secrets", f"{job.namespace}/{job.name}-ssh")


_PLUGINS = {p.name: p for p in (EnvPlugin(), SvcPlugin(), SSHPlugin())}


def get_job_plugin(name: str) -> JobPlugin:
    if name not in _PLUGINS:
        raise KeyError(f"unknown job plugin {name!r}")
    return _PLUGINS[name]
