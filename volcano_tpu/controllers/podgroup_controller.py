"""PodGroup controller: adopt bare pods into single-member PodGroups.

Reference: pkg/controllers/podgroup/ (294 LoC) — any pod with
``schedulerName: volcano`` and no group annotation gets a PodGroup created
for it so the gang machinery treats it uniformly
(createNormalPodPGIfNotExist, pg_controller_handler.go:75).
"""

from __future__ import annotations

from collections import deque
from typing import Deque

from ..api.core import POD_GROUP_ANNOTATION, Pod, PodGroup
from ..api.types import DEFAULT_QUEUE, DEFAULT_SCHEDULER_NAME
from .framework import Controller, register_controller


class PodGroupController(Controller):
    name = "podgroup-controller"

    def initialize(self, apiserver) -> None:
        self.api = apiserver
        self.queue: Deque[str] = deque()
        apiserver.watch("pods", self._on_pod)

    def _on_pod(self, event, pod: Pod, old) -> None:
        if event == "added":
            self.queue.append(pod.key)

    def process_all(self) -> None:
        while self.queue:
            self.sync_pod(self.queue.popleft())

    def sync_pod(self, pod_key: str) -> None:
        pod = self.api.get("pods", pod_key)
        if pod is None or pod.scheduler_name != DEFAULT_SCHEDULER_NAME:
            return
        if pod.annotations.get(POD_GROUP_ANNOTATION):
            return
        pg_name = f"podgroup-{pod.name}"
        if self.api.get("podgroups", f"{pod.namespace}/{pg_name}") is None:
            self.api.create("podgroups", PodGroup(
                name=pg_name, namespace=pod.namespace, min_member=1,
                queue=pod.annotations.get("volcano.sh/queue-name",
                                          DEFAULT_QUEUE)))
        pod.annotations[POD_GROUP_ANNOTATION] = pg_name


register_controller(PodGroupController)
