"""Lifecycle controllers (reference: pkg/controllers)."""

from .framework import (Controller, build_controllers, register_controller,
                        registered_controllers)
from .gc_controller import GarbageCollector
from .job_controller import JobController
from .job_state import Request, apply_policies
from .podgroup_controller import PodGroupController
from .queue_controller import QueueController

__all__ = [
    "Controller", "build_controllers", "register_controller",
    "registered_controllers", "GarbageCollector", "JobController",
    "PodGroupController", "QueueController", "Request", "apply_policies",
]
