"""Garbage collector: TTL-after-finished for Jobs.

Reference: pkg/controllers/garbagecollector/garbagecollector.go:40-291 —
finished jobs (Completed/Failed/Terminated/Aborted) with
``ttlSecondsAfterFinished`` set are deleted once the TTL expires, with
foreground propagation (pods/podgroup go too, handled by the job
controller's delete cleanup). The clock is injectable for tests, mirroring
garbagecollector_test.go:1-385.
"""

from __future__ import annotations

import time
from typing import Callable

from ..api.types import JobPhase
from .framework import Controller, register_controller

FINISHED = (JobPhase.COMPLETED, JobPhase.FAILED, JobPhase.TERMINATED,
            JobPhase.ABORTED)


class GarbageCollector(Controller):
    name = "gc"

    def initialize(self, apiserver, now: Callable[[], float] = time.time) -> None:
        self.api = apiserver
        self.now = now

    def process_all(self) -> None:
        for job in list(self.api.stores["jobs"].values()):
            if self.needs_cleanup(job):
                self.api.delete("jobs", job.key)

    def needs_cleanup(self, job) -> bool:
        """Reference: needsCleanup + processTTL (garbagecollector.go:150-220)."""
        if job.ttl_seconds_after_finished is None:
            return False
        if job.status.state.phase not in FINISHED:
            return False
        finish_time = job.status.state.transition_time or job.creation_timestamp
        return self.now() >= finish_time + job.ttl_seconds_after_finished


register_controller(GarbageCollector)
