"""Controller mini-framework: interface + registry.

Reference: pkg/controllers/framework/{interface.go:36-41, factory.go:24-46}.
The controller-manager instantiates every registered controller against the
shared API server and runs them (cmd/controller-manager/app/server.go).
"""

from __future__ import annotations

from typing import Dict, List, Type


class Controller:
    name: str = ""

    def initialize(self, apiserver) -> None:
        raise NotImplementedError

    def process_all(self) -> None:
        """Drain this controller's work queue (one reconcile sweep)."""
        pass


_REGISTRY: Dict[str, Type[Controller]] = {}


def register_controller(cls: Type[Controller]) -> None:
    _REGISTRY[cls.name] = cls


def registered_controllers() -> List[str]:
    return sorted(_REGISTRY)


def build_controllers(apiserver) -> List[Controller]:
    out = []
    for name in registered_controllers():
        c = _REGISTRY[name]()
        c.initialize(apiserver)
        out.append(c)
    return out
