"""Batch Job CRD types — the controller-side job model.

Reference: vendor/volcano.sh/apis/pkg/apis/batch/v1alpha1/job.go:32-310
(JobSpec/TaskSpec/LifecyclePolicy/JobStatus), bus command/event enums in
api.types. These are the objects users submit (vcctl job run), reconciled by
the job controller into pods + a PodGroup for the scheduler.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .job_info import Toleration
from .resource import Resource
from .types import BusAction, BusEvent, JobPhase


@dataclass
class PodTemplate:
    """Reduced pod template: what the scheduler and controllers consume."""

    resources: Dict[str, object] = field(default_factory=dict)  # ResourceList
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    #: node-affinity terms (NodeSelectorTerm or match-labels dicts):
    #: requiredDuringScheduling OR-of-terms and (term, weight) preferred
    affinity_required: List = field(default_factory=list)
    affinity_preferred: List = field(default_factory=list)
    priority: int = 0
    restart_policy: str = "OnFailure"
    volumes: List[str] = field(default_factory=list)    # volume claim names
    env: Dict[str, str] = field(default_factory=dict)

    def resreq(self) -> Resource:
        return Resource.from_resource_list(self.resources)


@dataclass
class LifecyclePolicy:
    """event/exit-code -> action matrix entry (job.go:143-180)."""

    action: BusAction
    event: Optional[BusEvent] = None
    events: List[BusEvent] = field(default_factory=list)
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None

    def matches_event(self, event: BusEvent) -> bool:
        evs = set(self.events)
        if self.event is not None:
            evs.add(self.event)
        return event in evs or BusEvent.ANY in evs

    def matches_exit_code(self, code: Optional[int]) -> bool:
        return (self.exit_code is not None and code is not None
                and self.exit_code == code)


@dataclass
class TaskSpec:
    """One role of the gang (job.go:182-213)."""

    name: str = ""
    replicas: int = 0
    template: PodTemplate = field(default_factory=PodTemplate)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    min_available: Optional[int] = None
    max_retry: int = 0


@dataclass
class VolumeSpec:
    """Job volume (job.go:106-141): an existing claim name or a size to
    provision a PVC for."""

    mount_path: str = ""
    volume_claim_name: str = ""
    storage: str = ""          # e.g. "1Gi" -> controller creates a PVC


@dataclass
class JobState:
    phase: JobPhase = JobPhase.PENDING
    reason: str = ""
    transition_time: float = 0.0


@dataclass
class JobStatus:
    """job.go:232-310."""

    state: JobState = field(default_factory=JobState)
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0
    retry_count: int = 0
    min_available: int = 0
    task_status_count: Dict[str, Dict[str, int]] = field(default_factory=dict)
    controlled_resources: Dict[str, str] = field(default_factory=dict)
    conditions: List[str] = field(default_factory=list)


@dataclass
class Job:
    """The batch.volcano.sh/v1alpha1 Job object."""

    name: str
    namespace: str = "default"
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    uid: str = ""

    # spec (job.go:48-105)
    scheduler_name: str = ""
    min_available: int = 0
    min_success: Optional[int] = None
    volumes: List[VolumeSpec] = field(default_factory=list)
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    queue: str = ""
    max_retry: int = 0
    ttl_seconds_after_finished: Optional[int] = None
    priority_class_name: str = ""

    status: JobStatus = field(default_factory=JobStatus)

    def __post_init__(self):
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def total_replicas(self) -> int:
        return sum(t.replicas for t in self.tasks)


@dataclass
class Command:
    """bus.volcano.sh/v1alpha1 Command: an action requested on a target
    object (vendor/.../bus/v1alpha1/commands.go:12-43)."""

    name: str
    namespace: str = "default"
    action: BusAction = BusAction.SYNC_JOB
    target_name: str = ""       # owner reference (job or queue name)
    target_kind: str = "Job"
    reason: str = ""
    message: str = ""
