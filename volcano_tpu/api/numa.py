"""Numatopology CRD types (nodeinfo.volcano.sh/v1alpha1).

Reference: vendor/volcano.sh/apis/pkg/apis/nodeinfo/v1alpha1/
numatopo_types.go:25-88. In the reference snapshot these are **types only** —
no scheduler consumer exists yet — so the parity obligation here is the data
model plus API-server storage (a cluster-scoped object per node), mirrored by
the "numatopologies" kind in runtime/apiserver.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

#: Manager policy names (numatopo_types.go:40-46).
CPU_MANAGER_POLICY = "CPUManagerPolicy"
TOPOLOGY_MANAGER_POLICY = "TopologyManagerPolicy"


@dataclass
class ResourceInfo:
    """Capacity/allocatable of one resource on a NUMA node
    (numatopo_types.go:26-29)."""

    allocatable: str = ""
    capacity: int = 0


@dataclass
class CPUInfo:
    """Topology detail of one logical CPU (numatopo_types.go:32-37)."""

    numa_node_id: int = 0
    socket_id: int = 0
    core_id: int = 0


@dataclass
class NumatopoSpec:
    """Reference: NumatopoSpec, numatopo_types.go:49-68."""

    policies: Dict[str, str] = field(default_factory=dict)
    res_reserved: Dict[str, str] = field(default_factory=dict)
    numa_res_map: Dict[str, ResourceInfo] = field(default_factory=dict)
    cpu_detail: Dict[str, CPUInfo] = field(default_factory=dict)


@dataclass
class Numatopology:
    """Cluster-scoped CRD, one per node, named after the node
    (numatopo_types.go:70-88)."""

    name: str
    spec: NumatopoSpec = field(default_factory=NumatopoSpec)

    @property
    def key(self) -> str:
        return self.name
