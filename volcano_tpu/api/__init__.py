"""In-memory scheduling data model (reference: pkg/scheduler/api)."""

from .cluster_info import ClusterInfo
from .job_info import (FitError, FitErrors, JobInfo, NodeSelectorTerm,
                       PodAffinityTerm, Taint, TaskInfo, Toleration,
                       as_node_term)
from .node_info import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE, GPUDevice,
                        NodeInfo, gpu_request_of)
from .numa import (CPU_MANAGER_POLICY, TOPOLOGY_MANAGER_POLICY, CPUInfo,
                   Numatopology, NumatopoSpec, ResourceInfo)
from .queue_info import (DEFAULT_NAMESPACE_WEIGHT, HIERARCHY_ANNOTATION,
                         HIERARCHY_WEIGHTS_ANNOTATION, NamespaceInfo, QueueInfo)
from .resource import (CPU, MEMORY, MIN_RESOURCE, PODS, Resource,
                       build_resource_list, parse_quantity)
from .types import (ALLOCATED_STATUSES, DEFAULT_QUEUE, DEFAULT_SCHEDULER_NAME,
                    BusAction, BusEvent, JobPhase, PodGroupPhase, QueueState,
                    TaskStatus, is_allocated_status)

__all__ = [
    "ClusterInfo", "FitError", "FitErrors", "JobInfo", "PodAffinityTerm",
    "Taint", "TaskInfo",
    "Toleration", "NodeInfo", "GPUDevice", "GPU_MEMORY_RESOURCE",
    "GPU_NUMBER_RESOURCE", "gpu_request_of", "NamespaceInfo", "QueueInfo",
    "Resource", "Numatopology", "NumatopoSpec", "CPUInfo", "ResourceInfo",
    "CPU_MANAGER_POLICY", "TOPOLOGY_MANAGER_POLICY",
    "build_resource_list", "parse_quantity", "CPU", "MEMORY", "PODS",
    "MIN_RESOURCE", "ALLOCATED_STATUSES", "DEFAULT_QUEUE",
    "DEFAULT_SCHEDULER_NAME", "DEFAULT_NAMESPACE_WEIGHT",
    "HIERARCHY_ANNOTATION", "HIERARCHY_WEIGHTS_ANNOTATION", "BusAction",
    "BusEvent", "JobPhase", "PodGroupPhase", "QueueState", "TaskStatus",
    "is_allocated_status",
]
