"""The full-cluster snapshot handed to a scheduling session.

Reference: ClusterInfo, pkg/scheduler/api/cluster_info.go:24-40 — the deep-copy
result of SchedulerCache.Snapshot (pkg/scheduler/cache/cache.go:712-811).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from .job_info import JobInfo
from .node_info import NodeInfo
from .queue_info import NamespaceInfo, QueueInfo


@dataclass
class PersistentVolumeClaim:
    """Scheduler-side PVC view — the volume-binding seam's input
    (defaultVolumeBinder.GetPodVolumes/AllocateVolumes, cache.go:240-272).

    ``bindable=False`` models FindPodVolumes failing everywhere (no
    matching PV / unbound claim with no provisioner); ``node_name`` models
    a local-PV node affinity pinning the claim (and every pod using it) to
    one node."""

    name: str
    bound: bool = False
    bindable: bool = True
    node_name: str = ""

    def clone(self) -> "PersistentVolumeClaim":
        return PersistentVolumeClaim(self.name, self.bound, self.bindable,
                                     self.node_name)


@dataclass
class ClusterInfo:
    jobs: Dict[str, JobInfo] = field(default_factory=dict)
    nodes: Dict[str, NodeInfo] = field(default_factory=dict)
    queues: Dict[str, QueueInfo] = field(default_factory=dict)
    namespaces: Dict[str, NamespaceInfo] = field(default_factory=dict)
    pvcs: Dict[str, PersistentVolumeClaim] = field(default_factory=dict)

    def add_job(self, job: JobInfo) -> None:
        self.jobs[job.uid] = job
        self.namespaces.setdefault(job.namespace, NamespaceInfo(job.namespace))

    def add_node(self, node: NodeInfo) -> None:
        self.nodes[node.name] = node

    def add_queue(self, queue: QueueInfo) -> None:
        self.queues[queue.name] = queue

    def total_resource(self):
        """Sum of node allocatables (cluster capacity) — the DRF denominator.

        Reference: total resource accumulation in drf.OnSessionOpen
        (pkg/scheduler/plugins/drf/drf.go:118-131)."""
        from .resource import Resource
        total = Resource()
        for node in self.nodes.values():
            total.add(node.allocatable)
        return total

    def clone(self) -> "ClusterInfo":
        return ClusterInfo(
            jobs={k: j.clone() for k, j in self.jobs.items()},
            nodes={k: n.clone() for k, n in self.nodes.items()},
            queues={k: q.clone() for k, q in self.queues.items()},
            namespaces={k: ns.clone() for k, ns in self.namespaces.items()},
            pvcs={k: p.clone() for k, p in self.pvcs.items()},
        )
