"""Core enums and constants of the scheduling data model.

Reference semantics: pkg/scheduler/api/types.go:29-113 (TaskStatus and helpers),
vendor/volcano.sh/apis/pkg/apis/scheduling/v1beta1/types.go:25-66 (PodGroup
phases), vendor/.../bus/v1alpha1/{actions.go,events.go} (bus actions/events),
vendor/.../batch/v1alpha1/job.go (Job phases).
"""

from __future__ import annotations

import enum


class TaskStatus(enum.IntEnum):
    """Lifecycle status of a task (pod).

    Reference: pkg/scheduler/api/types.go:29-61.
    """

    PENDING = 0      # not scheduled yet
    ALLOCATED = 1    # assigned to a node inside the session, not yet bound
    PIPELINED = 2    # assigned to a node whose resources are releasing
    BINDING = 3      # bind RPC in flight
    BOUND = 4        # bind acknowledged
    RUNNING = 5
    RELEASING = 6    # terminating; resources count as releasing on the node
    SUCCEEDED = 7
    FAILED = 8
    UNKNOWN = 9


#: Statuses that occupy node resources "now".
#: Reference: pkg/scheduler/api/types.go:87-96 (AllocatedStatus).
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.ALLOCATED, TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RUNNING}
)


def is_allocated_status(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


class JobPhase(str, enum.Enum):
    """Phase of a batch Job (controller-side state machine).

    Reference: vendor/volcano.sh/apis/pkg/apis/batch/v1alpha1/job.go (JobPhase)
    and pkg/controllers/job/state/factory.go:62-85.
    """

    PENDING = "Pending"
    ABORTING = "Aborting"
    ABORTED = "Aborted"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    COMPLETING = "Completing"
    COMPLETED = "Completed"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"
    FAILED = "Failed"


class PodGroupPhase(str, enum.Enum):
    """Scheduler-side gang phase.

    Reference: vendor/.../scheduling/v1beta1/types.go:25-43.
    """

    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


class QueueState(str, enum.Enum):
    """Reference: vendor/.../scheduling/v1beta1/types.go (QueueState)."""

    OPEN = "Open"
    CLOSED = "Closed"
    CLOSING = "Closing"
    UNKNOWN = "Unknown"


class BusAction(str, enum.Enum):
    """Actions carried by bus Commands / lifecycle policies.

    Reference: vendor/.../bus/v1alpha1/actions.go:20-60.
    """

    ABORT_JOB = "AbortJob"
    RESTART_JOB = "RestartJob"
    RESTART_TASK = "RestartTask"
    TERMINATE_JOB = "TerminateJob"
    COMPLETE_JOB = "CompleteJob"
    RESUME_JOB = "ResumeJob"
    SYNC_JOB = "SyncJob"
    ENQUEUE_JOB = "EnqueueJob"
    SYNC_QUEUE = "SyncQueue"
    OPEN_QUEUE = "OpenQueue"
    CLOSE_QUEUE = "CloseQueue"


class BusEvent(str, enum.Enum):
    """Events that trigger lifecycle policies.

    Reference: vendor/.../bus/v1alpha1/events.go:20-53.
    """

    ANY = "*"
    POD_FAILED = "PodFailed"
    POD_EVICTED = "PodEvicted"
    JOB_UNKNOWN = "Unknown"
    TASK_COMPLETED = "TaskCompleted"
    TASK_FAILED = "TaskFailed"
    OUT_OF_SYNC = "OutOfSync"
    COMMAND_ISSUED = "CommandIssued"
    JOB_UPDATED = "JobUpdated"


#: PodGroup condition types written by the gang plugin at session close.
#: Reference: pkg/scheduler/plugins/gang/gang.go:158-216.
POD_GROUP_CONDITION_UNSCHEDULABLE = "Unschedulable"
POD_GROUP_CONDITION_SCHEDULED = "Scheduled"

#: The default queue every unassigned job lands in.
#: Reference: pkg/scheduler/cache/cache.go (newDefaultQueue at startup).
DEFAULT_QUEUE = "default"

#: Default scheduler identity (pods opt in via schedulerName).
DEFAULT_SCHEDULER_NAME = "volcano"
