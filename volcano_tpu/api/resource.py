"""Resource vector algebra.

Re-design of the reference's dense resource arithmetic
(pkg/scheduler/api/resource_info.go:32-470): a Resource is a mapping of
resource-dimension name -> float quantity, with CPU in millicores and memory in
bytes, plus arbitrary scalar resources (GPUs, ephemeral storage, ...). The
arithmetic here is the host-side (Python) twin of the packed ``f32[R]`` device
vectors in :mod:`volcano_tpu.arrays`; both must agree, and the unit tests assert
the same algebraic identities the reference's resource_info_test.go does.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping, Optional

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"

#: Tiny quantities below which a dimension counts as empty.
#: Reference: minResource in pkg/scheduler/api/resource_info.go:27-30.
MIN_RESOURCE = 0.1

_SUFFIX = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}
_QTY_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(m|[kKMGTPE]i?)?$")


def parse_quantity(value, *, is_cpu: bool = False) -> float:
    """Parse a Kubernetes-style quantity string ("100m", "2Gi", "1.5") to float.

    CPU quantities are returned in millicores; everything else in base units.
    """
    if isinstance(value, (int, float)):
        return float(value) * (1000.0 if is_cpu else 1.0)
    m = _QTY_RE.match(str(value).strip())
    if not m:
        raise ValueError(f"unparseable quantity: {value!r}")
    num = float(m.group(1))
    suffix = m.group(2)
    if suffix == "m":
        milli = num
        return milli if is_cpu else num / 1000.0
    scale = _SUFFIX.get(suffix, 1.0) if suffix else 1.0
    base = num * scale
    return base * 1000.0 if is_cpu else base


class Resource:
    """A named resource vector.

    ``cpu`` is stored in millicores, ``memory`` in bytes; any other key is an
    opaque scalar resource. ``max_task_num`` mirrors the reference's
    ``MaxTaskNum`` (pod capacity, resource_info.go:44-47) and rides along
    without participating in the vector arithmetic.
    """

    __slots__ = ("quantities", "max_task_num")

    def __init__(self, quantities: Optional[Mapping[str, float]] = None,
                 max_task_num: Optional[int] = None):
        self.quantities: Dict[str, float] = dict(quantities or {})
        self.max_task_num = max_task_num

    # ---------------------------------------------------------------- factory
    @classmethod
    def from_resource_list(cls, rl: Mapping[str, object]) -> "Resource":
        """Build from a k8s-style ResourceList mapping (quantity strings ok).

        Reference: NewResource, resource_info.go:60-84.
        """
        q: Dict[str, float] = {}
        max_tasks: Optional[int] = None
        for name, val in (rl or {}).items():
            if name == CPU:
                q[CPU] = q.get(CPU, 0.0) + parse_quantity(val, is_cpu=True)
            elif name == PODS:
                max_tasks = int(parse_quantity(val))
            else:
                q[name] = q.get(name, 0.0) + parse_quantity(val)
        return cls(q, max_task_num=max_tasks)

    @classmethod
    def empty(cls) -> "Resource":
        return cls({})

    def clone(self) -> "Resource":
        return Resource(dict(self.quantities), self.max_task_num)

    # ---------------------------------------------------------------- access
    def get(self, name: str) -> float:
        return self.quantities.get(name, 0.0)

    @property
    def milli_cpu(self) -> float:
        return self.get(CPU)

    @property
    def memory(self) -> float:
        return self.get(MEMORY)

    def resource_names(self) -> Iterable[str]:
        return self.quantities.keys()

    def is_empty(self) -> bool:
        """Every dimension below MIN_RESOURCE. Reference: IsEmpty, resource_info.go:184-196."""
        return all(v < MIN_RESOURCE for v in self.quantities.values())

    def is_zero(self, name: str) -> bool:
        """Reference: IsZero, resource_info.go:198-210."""
        return self.get(name) < MIN_RESOURCE

    # ------------------------------------------------------------ arithmetic
    def add(self, other: "Resource") -> "Resource":
        """In-place add. Reference: Add, resource_info.go:230-242."""
        for name, v in other.quantities.items():
            self.quantities[name] = self.quantities.get(name, 0.0) + v
        return self

    def sub(self, other: "Resource") -> "Resource":
        """In-place subtract; raises if other is not <= self.

        Reference: Sub, resource_info.go:244-258 (panics on underflow).
        """
        if not other.less_equal(self):
            raise ValueError(f"resource underflow: {other} not <= {self}")
        for name, v in other.quantities.items():
            self.quantities[name] = self.quantities.get(name, 0.0) - v
        return self

    def sub_floored(self, other: "Resource") -> "Resource":
        """In-place subtract clamped at zero (used for Diff-style accounting)."""
        for name, v in other.quantities.items():
            self.quantities[name] = max(0.0, self.quantities.get(name, 0.0) - v)
        return self

    def multi(self, ratio: float) -> "Resource":
        """In-place scale. Reference: Multi, resource_info.go:260-270."""
        for name in self.quantities:
            self.quantities[name] *= ratio
        return self

    def set_max_resource(self, other: "Resource") -> "Resource":
        """Element-wise max. Reference: SetMaxResource, resource_info.go:272-292."""
        for name, v in other.quantities.items():
            if v > self.quantities.get(name, 0.0):
                self.quantities[name] = v
        return self

    def min_dimension_resource(self, other: "Resource") -> "Resource":
        """Element-wise min over self's dimensions.

        Reference: MinDimensionResource, resource_info.go:294-330 (zero-fill
        semantics: dimensions missing from other clamp to 0).
        """
        for name in list(self.quantities):
            self.quantities[name] = min(self.quantities[name], other.get(name))
        return self

    def fit_delta(self, other: "Resource") -> "Resource":
        """Add other with a MIN_RESOURCE epsilon on each of other's nonzero
        dims so that subsequent LessEqual checks are strict fits.

        Reference: FitDelta, resource_info.go:212-228.
        """
        for name, v in other.quantities.items():
            if v > 0:
                self.quantities[name] = self.quantities.get(name, 0.0) + v + MIN_RESOURCE
        return self

    # ------------------------------------------------------------ comparison
    def less_equal(self, other: "Resource") -> bool:
        """self <= other on every dimension of self (missing = 0).

        Reference: LessEqual with zero semantics, resource_info.go:376-414.
        """
        return all(v <= other.get(name) + 1e-9 for name, v in self.quantities.items())

    def less_equal_strict(self, other: "Resource") -> bool:
        """Strict <= requiring every dim of self to exist in other.

        Reference: LessEqualStrict, resource_info.go:416-430.
        """
        return all(
            name in other.quantities and v <= other.quantities[name] + 1e-9
            for name, v in self.quantities.items()
        )

    def less(self, other: "Resource") -> bool:
        """self < other on EVERY dimension. Reference: Less, resource_info.go:332-360."""
        if not self.quantities and not other.quantities:
            return False
        names = set(self.quantities) | set(other.quantities)
        return all(self.get(n) < other.get(n) for n in names)

    def less_partly(self, other: "Resource") -> bool:
        """self < other on AT LEAST one dimension.

        Reference: LessPartly, resource_info.go (used by reclaim/overused checks).
        """
        names = set(self.quantities) | set(other.quantities)
        return any(self.get(n) < other.get(n) for n in names)

    def diff(self, other: "Resource") -> tuple["Resource", "Resource"]:
        """Return (increased, decreased) vs other.

        Reference: Diff, resource_info.go:432-470.
        """
        inc, dec = Resource(), Resource()
        names = set(self.quantities) | set(other.quantities)
        for n in names:
            d = self.get(n) - other.get(n)
            if d > 0:
                inc.quantities[n] = d
            elif d < 0:
                dec.quantities[n] = -d
        return inc, dec

    # ---------------------------------------------------------------- dunder
    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        names = set(self.quantities) | set(other.quantities)
        return all(abs(self.get(n) - other.get(n)) < 1e-6 for n in names)

    def __hash__(self):  # pragma: no cover - Resources are not hashed
        raise TypeError("Resource is mutable and unhashable")

    def __repr__(self) -> str:
        parts = ", ".join(f"{k}={v:g}" for k, v in sorted(self.quantities.items()))
        return f"Resource({parts})"


def build_resource_list(cpu: str | float = 0, memory: str | float = 0,
                        **scalars) -> Dict[str, object]:
    """Test/fixture helper mirroring util.BuildResourceList
    (pkg/scheduler/util/test_utils.go:30-45)."""
    rl: Dict[str, object] = {}
    if cpu:
        rl[CPU] = cpu
    if memory:
        rl[MEMORY] = memory
    rl.update(scalars)
    return rl
