"""Queue and namespace projections.

Reference: QueueInfo (pkg/scheduler/api/queue_info.go:27-88, including the
fork's hierarchical-DRF fields parsed from the ``volcano.sh/hierarchy`` and
``volcano.sh/hierarchy-weights`` annotations) and NamespaceInfo
(pkg/scheduler/api/namespace_info.go:28-145).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .resource import Resource
from .types import QueueState

HIERARCHY_ANNOTATION = "volcano.sh/hierarchy"
HIERARCHY_WEIGHTS_ANNOTATION = "volcano.sh/hierarchy-weights"

#: Default namespace weight when no ResourceQuota sets one.
#: Reference: DefaultNamespaceWeight, namespace_info.go:35.
DEFAULT_NAMESPACE_WEIGHT = 1


@dataclass
class QueueInfo:
    name: str
    weight: int = 1
    capability: Resource = field(default_factory=Resource)
    reclaimable: bool = True
    state: QueueState = QueueState.OPEN
    hierarchy: str = ""          # "/root/sci/dev" style path
    hierarchy_weights: str = ""  # "1/2/3" weights along the path
    annotations: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        if not self.hierarchy:
            self.hierarchy = self.annotations.get(HIERARCHY_ANNOTATION, "")
        if not self.hierarchy_weights:
            self.hierarchy_weights = self.annotations.get(
                HIERARCHY_WEIGHTS_ANNOTATION, "")

    def hierarchy_path(self) -> List[str]:
        return [p for p in self.hierarchy.split("/") if p]

    def hierarchy_weight_values(self) -> List[float]:
        return [float(w) for w in self.hierarchy_weights.split("/") if w]

    def is_open(self) -> bool:
        return self.state == QueueState.OPEN

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.name, self.weight, self.capability.clone(),
                         self.reclaimable, self.state, self.hierarchy,
                         self.hierarchy_weights, dict(self.annotations))


@dataclass
class NamespaceInfo:
    """Namespace with fairness weight from its ResourceQuota.

    Reference: NamespaceInfo/NamespaceCollection, namespace_info.go:28-145
    (weight = max over quotas of the ``volcano.sh/namespace.weight`` hard limit).
    """

    name: str
    weight: int = DEFAULT_NAMESPACE_WEIGHT

    def clone(self) -> "NamespaceInfo":
        return NamespaceInfo(self.name, self.weight)
