"""Task and gang-job projections of the cluster model.

Reference semantics: pkg/scheduler/api/job_info.go:70-613 (TaskInfo, JobInfo),
pkg/scheduler/api/unschedule_info.go:20-101 (FitErrors). The new design keeps
the same invariants (status index, Ready()/Pipelined()/Starving() arithmetic,
per-role minAvailable) but as plain dataclasses that the array packer
(:mod:`volcano_tpu.arrays.pack`) can flatten into device tensors.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

from .resource import Resource
from .types import TaskStatus, PodGroupPhase, is_allocated_status


@dataclass
class Toleration:
    """Pod toleration. Reference: k8s core/v1 Toleration as consumed by
    the tainttoleration predicate (pkg/scheduler/plugins/predicates)."""

    key: str = ""
    operator: str = "Equal"   # Equal | Exists
    value: str = ""
    effect: str = ""          # "", NoSchedule, PreferNoSchedule, NoExecute

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and taint.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class PodAffinityTerm:
    """One inter-pod (anti-)affinity term.

    Reference semantics: the k8s InterPodAffinity plugin the reference wraps
    (pkg/scheduler/plugins/predicates/predicates.go:196-200 filter dispatch
    261-273; nodeorder.go:273-306 batch scorer). A term selects existing
    pods by label selector within ``namespaces`` (empty = the incoming
    task's own namespace) and constrains placement relative to the topology
    domain — the set of nodes sharing the same value of ``topology_key`` —
    that the matched pods occupy. ``weight`` is used by preferred terms
    only (0 for required terms).
    """

    topology_key: str = "kubernetes.io/hostname"
    match_labels: Dict[str, str] = field(default_factory=dict)
    # (key, op, values) with op in In/NotIn/Exists/DoesNotExist
    match_expressions: List[tuple] = field(default_factory=list)
    namespaces: List[str] = field(default_factory=list)
    weight: int = 0

    def matches(self, labels: Dict[str, str], namespace: str,
                own_namespace: str) -> bool:
        """Full k8s label-selector semantics, evaluated host-side."""
        allowed_ns = self.namespaces or [own_namespace]
        if namespace not in allowed_ns:
            return False
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for key, op, values in self.match_expressions:
            present = key in labels
            if op == "In":
                if not present or labels[key] not in values:
                    return False
            elif op == "NotIn":
                if present and labels[key] in values:
                    return False
            elif op == "Exists":
                if not present:
                    return False
            elif op == "DoesNotExist":
                if present:
                    return False
            else:
                raise ValueError(f"unknown selector op {op!r}")
        return True

    def clone(self) -> "PodAffinityTerm":
        return PodAffinityTerm(
            topology_key=self.topology_key,
            match_labels=dict(self.match_labels),
            match_expressions=[tuple(e) for e in self.match_expressions],
            namespaces=list(self.namespaces), weight=self.weight)


@dataclass
class NodeSelectorTerm:
    """One required/preferred nodeSelectorTerm: AND of matchLabels equality
    pairs and matchExpressions with the full k8s operator set
    In / NotIn / Exists / DoesNotExist / Gt / Lt.

    Reference semantics: the wrapped k8s NodeAffinity plugin that the
    predicates filter and nodeorder scorer delegate to
    (pkg/scheduler/plugins/predicates/predicates.go:186-190,
    pkg/scheduler/plugins/nodeorder/nodeorder.go:255-266), i.e.
    component-helpers nodeaffinity.NodeSelectorRequirementsAsSelector:
    In requires the label present with a value in the set; NotIn and
    DoesNotExist also match when the label is absent; Gt/Lt parse the
    label value as an integer and require exactly one integer operand
    (parse failures match nothing). A term with no labels and no
    expressions matches no nodes (k8s: "a null or empty node selector
    term matches no objects")."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    #: (key, op, values) tuples; values is a tuple/list of strings
    match_expressions: List[tuple] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        if not self.match_labels and not self.match_expressions:
            return False
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for key, op, values in self.match_expressions:
            present = key in labels
            if op == "In":
                if not present or labels[key] not in values:
                    return False
            elif op == "NotIn":
                if present and labels[key] in values:
                    return False
            elif op == "Exists":
                if not present:
                    return False
            elif op == "DoesNotExist":
                if present:
                    return False
            elif op in ("Gt", "Lt"):
                if not present or len(values) != 1:
                    return False
                try:
                    lv = int(str(labels[key]).strip())
                    rv = int(str(values[0]).strip())
                except ValueError:
                    return False
                if not (lv > rv if op == "Gt" else lv < rv):
                    return False
            else:
                raise ValueError(f"unknown node-selector op {op!r}")
        return True

    def is_pure_labels(self) -> bool:
        return not self.match_expressions

    def signature(self) -> tuple:
        return (tuple(sorted(self.match_labels.items())),
                tuple((k, op, tuple(v)) for k, op, v
                      in self.match_expressions))

    def clone(self) -> "NodeSelectorTerm":
        return NodeSelectorTerm(
            match_labels=dict(self.match_labels),
            match_expressions=[(k, op, tuple(v)) for k, op, v
                               in self.match_expressions])


def as_node_term(term) -> NodeSelectorTerm:
    """Normalize a node-affinity term: plain dicts (the original
    match-labels-only shape) become expression-less terms."""
    if isinstance(term, NodeSelectorTerm):
        return term
    return NodeSelectorTerm(match_labels=dict(term))


@dataclass
class TaskInfo:
    """A schedulable unit (pod) of a gang job.

    Reference: TaskInfo + NewTaskInfo, pkg/scheduler/api/job_info.go:70-171.
    """

    uid: str
    name: str
    namespace: str = "default"
    job: str = ""                       # JobInfo key "ns/name"
    task_role: str = ""                 # template (task spec) name
    resreq: Resource = field(default_factory=Resource)
    init_resreq: Resource = field(default_factory=Resource)
    status: TaskStatus = TaskStatus.PENDING
    priority: int = 0
    node_name: str = ""                 # assigned node ("" = unassigned)
    gpu_index: int = -1                 # assigned shared-GPU card (GPUIndex
    #                                     annotation, well_known_labels.go:28)
    preemptable: bool = False
    best_effort: bool = False
    revocable_zone: str = ""
    priority_class: str = ""            # Pod.Spec.PriorityClassName (the
    #                                     conformance veto input,
    #                                     conformance.go:48-55)
    host_ports: List[int] = field(default_factory=list)  # container
    #                                     hostPorts (the k8s NodePorts
    #                                     filter input, predicates.go:191)
    pvcs: List[str] = field(default_factory=list)  # claim names (the
    #                                     volume-binding seam input,
    #                                     cache.go:240-272)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    #: requiredDuringSchedulingIgnoredDuringExecution nodeSelectorTerms —
    #: OR-of-terms; each entry is a NodeSelectorTerm or a plain match-labels
    #: dict (normalized via as_node_term)
    affinity_required: List = field(default_factory=list)
    #: preferredDuringSchedulingIgnoredDuringExecution node-affinity terms
    #: as (term-or-match-labels, weight) pairs — the k8s NodeAffinity
    #: scorer input (nodeorder.go:255-266)
    affinity_preferred: List[Tuple] = field(default_factory=list)
    # inter-pod (anti-)affinity terms (k8s InterPodAffinity semantics,
    # predicates.go:261-273 + nodeorder.go:273-306):
    pod_affinity: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity: List[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_preferred: List[PodAffinityTerm] = field(
        default_factory=list)

    def __post_init__(self):
        if not self.init_resreq.quantities:
            self.init_resreq = self.resreq.clone()
        self.best_effort = self.resreq.is_empty()
        # a preemptable pod may use every revocable zone unless it pins one
        # (GetPodRevocableZone: preemptable=true -> "*", job_info.go:340-358;
        # only ""/"*" are supported values in this fork)
        if not self.revocable_zone and self.preemptable:
            self.revocable_zone = "*"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "TaskInfo":
        t = TaskInfo(
            uid=self.uid, name=self.name, namespace=self.namespace, job=self.job,
            task_role=self.task_role, resreq=self.resreq.clone(),
            init_resreq=self.init_resreq.clone(), status=self.status,
            priority=self.priority, node_name=self.node_name,
            gpu_index=self.gpu_index,
            preemptable=self.preemptable, revocable_zone=self.revocable_zone,
            priority_class=self.priority_class,
            host_ports=list(self.host_ports), pvcs=list(self.pvcs),
            node_selector=dict(self.node_selector),
            tolerations=list(self.tolerations), labels=dict(self.labels),
            affinity_required=[as_node_term(m).clone()
                               if isinstance(m, NodeSelectorTerm) else dict(m)
                               for m in self.affinity_required],
            affinity_preferred=[(m.clone() if isinstance(m, NodeSelectorTerm)
                                 else dict(m), w)
                                for m, w in self.affinity_preferred],
            pod_affinity=[t.clone() for t in self.pod_affinity],
            pod_anti_affinity=[t.clone() for t in self.pod_anti_affinity],
            pod_affinity_preferred=[
                t.clone() for t in self.pod_affinity_preferred],
            pod_anti_affinity_preferred=[
                t.clone() for t in self.pod_anti_affinity_preferred],
        )
        t.best_effort = self.best_effort
        return t


@dataclass
class FitError:
    """Why a task failed on a node. Reference: unschedule_info.go:20-60."""

    task: str
    node: str
    reasons: List[str]

    def __str__(self) -> str:
        return f"task {self.task} on node {self.node}: {'; '.join(self.reasons)}"


class FitErrors:
    """Per-job aggregation of fit errors. Reference: unschedule_info.go:62-101."""

    def __init__(self):
        self.errors: Dict[str, FitError] = {}

    def set_node_error(self, node: str, err: FitError) -> None:
        self.errors[node] = err

    def __str__(self) -> str:
        return "; ".join(str(e) for e in self.errors.values())


class JobInfo:
    """A gang job: the scheduler-side projection of a PodGroup plus its pods.

    Reference: JobInfo, pkg/scheduler/api/job_info.go:181-613.
    """

    def __init__(self, uid: str, name: str = "", namespace: str = "default",
                 queue: str = "default", priority: int = 0,
                 min_available: int = 0,
                 task_min_available: Optional[Mapping[str, int]] = None,
                 min_resources: Optional[Resource] = None,
                 creation_timestamp: float = 0.0,
                 pod_group_phase: PodGroupPhase = PodGroupPhase.PENDING,
                 preemptable: bool = False,
                 budget_min_available: str = "",
                 budget_max_unavailable: str = "",
                 sla_waiting_time: str = "",
                 annotations: Optional[Mapping[str, str]] = None):
        self.uid = uid
        self.name = name or uid.split("/")[-1]
        self.namespace = namespace
        self.queue = queue
        self.priority = priority
        self.min_available = min_available
        self.task_min_available: Dict[str, int] = dict(task_min_available or {})
        self.min_resources = min_resources or Resource()
        self.creation_timestamp = creation_timestamp or time.time()
        self.pod_group_phase = pod_group_phase
        self.preemptable = preemptable
        # DisruptionBudget from the PodGroup's JDB annotations (int or
        # percentage strings; job_info.go:38-52 + extractBudget :361-372)
        self.budget_min_available = budget_min_available
        self.budget_max_unavailable = budget_max_unavailable
        # per-job SLA annotation (sla-waiting-time, sla.go:79-82)
        self.sla_waiting_time = sla_waiting_time
        # raw PodGroup annotations (task-topology groups, etc.)
        self.annotations: Dict[str, str] = dict(annotations or {})

        self.tasks: Dict[str, TaskInfo] = {}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        self.allocated = Resource()      # resources of allocated-status tasks
        self.total_request = Resource()
        self.fit_errors: Dict[str, FitErrors] = {}   # task uid -> node errors
        self.job_fit_errors: str = ""

    # --------------------------------------------------------------- mutation
    def add_task(self, task: TaskInfo) -> None:
        """Reference: AddTaskInfo, job_info.go:300-320."""
        task.job = self.uid
        self.tasks[task.uid] = task
        self._index(task)
        self.total_request.add(task.resreq)
        if is_allocated_status(task.status):
            self.allocated.add(task.resreq)

    def delete_task(self, task: TaskInfo) -> None:
        stored = self.tasks.pop(task.uid, None)
        if stored is None:
            return
        self._unindex(stored)
        self.total_request.sub_floored(stored.resreq)
        if is_allocated_status(stored.status):
            self.allocated.sub_floored(stored.resreq)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """Reference: UpdateTaskStatus, job_info.go:402-420."""
        stored = self.tasks.get(task.uid)
        if stored is None:
            return
        if is_allocated_status(stored.status):
            self.allocated.sub_floored(stored.resreq)
        self._unindex(stored)
        stored.status = status
        self._index(stored)
        if is_allocated_status(status):
            self.allocated.add(stored.resreq)

    def _index(self, task: TaskInfo) -> None:
        self.task_status_index.setdefault(task.status, {})[task.uid] = task

    def _unindex(self, task: TaskInfo) -> None:
        bucket = self.task_status_index.get(task.status)
        if bucket:
            bucket.pop(task.uid, None)
            if not bucket:
                del self.task_status_index[task.status]

    # ------------------------------------------------------------- accounting
    def _count(self, *statuses: TaskStatus) -> int:
        return sum(len(self.task_status_index.get(s, {})) for s in statuses)

    def ready_task_num(self) -> int:
        """Tasks occupying resources now (Allocated|Binding|Bound|Running) plus
        Succeeded. Reference: ReadyTaskNum, job_info.go:560-575."""
        return self._count(TaskStatus.ALLOCATED, TaskStatus.BINDING,
                           TaskStatus.BOUND, TaskStatus.RUNNING,
                           TaskStatus.SUCCEEDED)

    def waiting_task_num(self) -> int:
        """Pipelined tasks. Reference: WaitingTaskNum, job_info.go:577-585."""
        return self._count(TaskStatus.PIPELINED)

    def pending_task_num(self) -> int:
        return self._count(TaskStatus.PENDING)

    def valid_task_num(self) -> int:
        """Tasks in a schedulable/occupying state.

        Reference: ValidTaskNum, job_info.go (Pending|Allocated|Bound|Binding|
        Running|Pipelined|Succeeded)."""
        return self._count(TaskStatus.PENDING, TaskStatus.ALLOCATED,
                           TaskStatus.BOUND, TaskStatus.BINDING,
                           TaskStatus.RUNNING, TaskStatus.PIPELINED,
                           TaskStatus.SUCCEEDED)

    def is_ready(self) -> bool:
        """Gang admission: ready >= minAvailable. Reference: Ready, job_info.go:596-600."""
        return self.ready_task_num() >= self.min_available

    def is_pipelined(self) -> bool:
        """Reference: gang JobPipelined — waiting + ready >= minAvailable
        (pkg/scheduler/plugins/gang/gang.go:140-148)."""
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    def is_starving(self) -> bool:
        """Reference: gang JobStarving (gang.go:150-155)."""
        return not self.is_ready() and not self.is_pipelined()

    def check_task_min_available(self) -> bool:
        """Per-role minAvailable across valid tasks.

        Reference: CheckTaskMinAvailable, job_info.go:552-575."""
        if not self.task_min_available:
            return True
        actual: Dict[str, int] = {}
        for task in self.tasks.values():
            if task.status in (TaskStatus.PENDING, TaskStatus.ALLOCATED,
                               TaskStatus.BOUND, TaskStatus.BINDING,
                               TaskStatus.RUNNING, TaskStatus.PIPELINED,
                               TaskStatus.SUCCEEDED):
                actual[task.task_role] = actual.get(task.task_role, 0) + 1
        return all(actual.get(role, 0) >= need
                   for role, need in self.task_min_available.items())

    def is_valid(self) -> tuple[bool, str]:
        """Gang JobValid: enough valid tasks for minAvailable and per-role
        minima. Reference: gang.go:52-81."""
        if self.valid_task_num() < self.min_available:
            return False, (f"job {self.uid} has {self.valid_task_num()} valid tasks, "
                           f"less than minAvailable {self.min_available}")
        if not self.check_task_min_available():
            return False, f"job {self.uid} does not satisfy per-task minAvailable"
        return True, ""

    def pending_tasks(self) -> List[TaskInfo]:
        return list(self.task_status_index.get(TaskStatus.PENDING, {}).values())

    def clone(self) -> "JobInfo":
        """Deep copy. Reference: Clone, job_info.go:448-478."""
        j = JobInfo(self.uid, self.name, self.namespace, self.queue,
                    self.priority, self.min_available, self.task_min_available,
                    self.min_resources.clone(), self.creation_timestamp,
                    self.pod_group_phase, self.preemptable,
                    self.budget_min_available, self.budget_max_unavailable,
                    self.sla_waiting_time, self.annotations)
        for task in self.tasks.values():
            j.add_task(task.clone())
        return j

    def __repr__(self) -> str:
        return (f"JobInfo({self.uid}, queue={self.queue}, prio={self.priority}, "
                f"minAvailable={self.min_available}, tasks={len(self.tasks)})")
