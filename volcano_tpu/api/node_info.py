"""Per-node resource accounting.

Reference: NodeInfo, pkg/scheduler/api/node_info.go:28-437. Invariants kept:
``idle + used == allocatable``; ``future_idle = idle + releasing - pipelined``
(node_info.go:62-65); task add/remove moves quantities between the buckets by
task status.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .job_info import Taint, TaskInfo
from .resource import Resource
from .types import TaskStatus, is_allocated_status


@dataclass
class NodeInfo:
    name: str
    allocatable: Resource = field(default_factory=Resource)
    capability: Resource = field(default_factory=Resource)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    ready: bool = True
    max_pods: int = 110

    def __post_init__(self):
        if not self.capability.quantities:
            self.capability = self.allocatable.clone()
        if self.allocatable.max_task_num is not None:
            self.max_pods = self.allocatable.max_task_num
        self.idle = self.allocatable.clone()
        self.used = Resource()
        self.releasing = Resource()
        self.pipelined = Resource()
        self.tasks: Dict[str, TaskInfo] = {}

    # ----------------------------------------------------------------- state
    def future_idle(self) -> Resource:
        """idle + releasing - pipelined. Reference: FutureIdle, node_info.go:62-65."""
        return self.idle.clone().add(self.releasing).sub_floored(self.pipelined)

    def pod_count(self) -> int:
        return len(self.tasks)

    # -------------------------------------------------------------- mutation
    def add_task(self, task: TaskInfo) -> None:
        """Reference: AddTask, node_info.go:247-292."""
        if task.uid in self.tasks:
            raise ValueError(f"task {task.uid} already on node {self.name}")
        if task.status == TaskStatus.RELEASING:
            self.used.add(task.resreq)
            self.releasing.add(task.resreq)
            self.idle.sub(task.resreq)
        elif task.status == TaskStatus.PIPELINED:
            self.pipelined.add(task.resreq)
        elif is_allocated_status(task.status):
            self.used.add(task.resreq)
            self.idle.sub(task.resreq)
        # terminal statuses (Succeeded/Failed) occupy nothing
        task.node_name = self.name
        self.tasks[task.uid] = task

    def remove_task(self, task: TaskInfo) -> None:
        """Reference: RemoveTask, node_info.go:294-326."""
        stored = self.tasks.pop(task.uid, None)
        if stored is None:
            return
        if stored.status == TaskStatus.RELEASING:
            self.used.sub_floored(stored.resreq)
            self.releasing.sub_floored(stored.resreq)
            self.idle.add(stored.resreq)
        elif stored.status == TaskStatus.PIPELINED:
            self.pipelined.sub_floored(stored.resreq)
        elif is_allocated_status(stored.status):
            self.used.sub_floored(stored.resreq)
            self.idle.add(stored.resreq)

    def update_task(self, task: TaskInfo) -> None:
        """Reference: UpdateTask, node_info.go:328-340."""
        self.remove_task(task)
        self.add_task(task)

    def clone(self) -> "NodeInfo":
        n = NodeInfo(self.name, self.allocatable.clone(), self.capability.clone(),
                     dict(self.labels), list(self.taints), self.unschedulable,
                     self.ready, self.max_pods)
        for task in self.tasks.values():
            n.add_task(task.clone())
        return n

    def __repr__(self) -> str:
        return f"NodeInfo({self.name}, idle={self.idle}, used={self.used})"
