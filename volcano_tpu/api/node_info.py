"""Per-node resource accounting.

Reference: NodeInfo, pkg/scheduler/api/node_info.go:28-437. Invariants kept:
``idle + used == allocatable``; ``future_idle = idle + releasing - pipelined``
(node_info.go:62-65); task add/remove moves quantities between the buckets by
task status.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .job_info import Taint, TaskInfo
from .resource import Resource
from .types import TaskStatus, is_allocated_status

#: Extended resource name for shared-GPU memory requests.
#: Reference: VolcanoGPUResource, pkg/scheduler/api/well_known_labels.go:22.
GPU_MEMORY_RESOURCE = "volcano.sh/gpu-memory"
#: Extended resource name declaring the virtual GPU card count of a node.
#: Reference: VolcanoGPUNumber, well_known_labels.go:24.
GPU_NUMBER_RESOURCE = "volcano.sh/gpu-number"


@dataclass
class GPUDevice:
    """One shareable GPU card: id, memory capacity, and per-task usage.

    Reference: GPUDevice, pkg/scheduler/api/device_info.go:24-53 (PodMap of
    sharing pods -> here a task_uid -> requested-memory map).
    """

    id: int
    memory: float
    used_by: Dict[str, float] = field(default_factory=dict)

    def used_memory(self) -> float:
        """Reference: getUsedGPUMemory, device_info.go:42-53."""
        return sum(self.used_by.values())

    def idle_memory(self) -> float:
        return self.memory - self.used_memory()


def gpu_request_of(resreq: Resource) -> float:
    """GPU memory requested by a task (GetGPUResourceOfPod, device_info.go:56-62)."""
    return resreq.get(GPU_MEMORY_RESOURCE)


@dataclass
class NodeInfo:
    name: str
    allocatable: Resource = field(default_factory=Resource)
    capability: Resource = field(default_factory=Resource)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    ready: bool = True
    max_pods: int = 110

    def __post_init__(self):
        if not self.capability.quantities:
            self.capability = self.allocatable.clone()
        if self.allocatable.max_task_num is not None:
            self.max_pods = self.allocatable.max_task_num
        self.idle = self.allocatable.clone()
        self.used = Resource()
        self.releasing = Resource()
        self.pipelined = Resource()
        self.tasks: Dict[str, TaskInfo] = {}
        # GPU cards from the node's declared gpu-memory / gpu-number capacity
        # (setNodeGPUInfo, node_info.go:171-195): memory is split evenly.
        self.gpu_devices: List[GPUDevice] = []
        total_mem = self.capability.get(GPU_MEMORY_RESOURCE) or \
            self.allocatable.get(GPU_MEMORY_RESOURCE)
        n_cards = int(self.capability.get(GPU_NUMBER_RESOURCE) or
                      self.allocatable.get(GPU_NUMBER_RESOURCE))
        if total_mem > 0 and n_cards > 0:
            per_card = total_mem / n_cards
            self.gpu_devices = [GPUDevice(i, per_card) for i in range(n_cards)]
        # tasks with an in-flight bind RPC (fork feature: such nodes are
        # skipped by Snapshot until the bind lands; node_info.go:54-56,
        # cache.go:735-738)
        self.binding_tasks: set = set()
        self.state_reason: str = ""

    # ----------------------------------------------------------------- state
    def future_idle(self) -> Resource:
        """idle + releasing - pipelined. Reference: FutureIdle, node_info.go:62-65."""
        return self.idle.clone().add(self.releasing).sub_floored(self.pipelined)

    def pod_count(self) -> int:
        return len(self.tasks)

    # -------------------------------------------------------------- mutation
    def add_task(self, task: TaskInfo, force: bool = False) -> None:
        """Reference: AddTask, node_info.go:247-292. Raises without mutating
        when the task cannot fit current idle (allocateIdleResource,
        node_info.go:235-242). ``force`` is for cache ingestion of
        already-running pods: usage is accounted even past allocatable, and
        :meth:`sync_state` then flags the node OutOfSync — the reference
        reaches the same state by keeping stale tasks across SetNode
        (setNodeState, node_info.go:143-149)."""
        if task.uid in self.tasks:
            raise ValueError(f"task {task.uid} already on node {self.name}")
        occupies = (task.status == TaskStatus.RELEASING
                    or is_allocated_status(task.status))
        if occupies and not force and not task.resreq.less_equal(self.idle):
            raise ValueError(
                f"selected node NotReady: {task.uid} does not fit idle of "
                f"{self.name}")
        if task.status == TaskStatus.RELEASING:
            self.used.add(task.resreq)
            self.releasing.add(task.resreq)
            self.idle.sub_floored(task.resreq)
        elif task.status == TaskStatus.PIPELINED:
            self.pipelined.add(task.resreq)
        elif is_allocated_status(task.status):
            self.used.add(task.resreq)
            self.idle.sub_floored(task.resreq)
        # terminal statuses (Succeeded/Failed) occupy nothing — including GPU
        # cards (getUsedGPUMemory skips Succeeded/Failed pods,
        # device_info.go:42-53)
        if task.status == TaskStatus.RELEASING or is_allocated_status(task.status):
            self.add_gpu_resource(task)
        task.node_name = self.name
        self.tasks[task.uid] = task

    def remove_task(self, task: TaskInfo) -> None:
        """Reference: RemoveTask, node_info.go:294-326."""
        stored = self.tasks.pop(task.uid, None)
        if stored is None:
            return
        if stored.status == TaskStatus.RELEASING:
            self.used.sub_floored(stored.resreq)
            self.releasing.sub_floored(stored.resreq)
            self.idle.add(stored.resreq)
        elif stored.status == TaskStatus.PIPELINED:
            self.pipelined.sub_floored(stored.resreq)
        elif is_allocated_status(stored.status):
            self.used.sub_floored(stored.resreq)
            self.idle.add(stored.resreq)
        self.sub_gpu_resource(stored)

    # ----------------------------------------------------------- gpu sharing
    def add_gpu_resource(self, task: TaskInfo) -> None:
        """Charge the task's GPU memory to its assigned card
        (AddGPUResource, node_info.go:395-404)."""
        req = gpu_request_of(task.resreq)
        if req > 0 and 0 <= task.gpu_index < len(self.gpu_devices):
            self.gpu_devices[task.gpu_index].used_by[task.uid] = req

    def sub_gpu_resource(self, task: TaskInfo) -> None:
        """Reference: SubGPUResource, node_info.go:406-415."""
        if 0 <= task.gpu_index < len(self.gpu_devices):
            self.gpu_devices[task.gpu_index].used_by.pop(task.uid, None)

    def idle_gpu_memory(self) -> List[float]:
        """Per-card idle memory (GetDevicesIdleGPUMemory, node_info.go:365-377)."""
        return [d.idle_memory() for d in self.gpu_devices]

    def predicate_gpu(self, task: TaskInfo) -> int:
        """Lowest card id whose idle memory fits the task's request, or -1
        (predicateGPU, plugins/predicates/gpu.go:41-56)."""
        req = gpu_request_of(task.resreq)
        if req <= 0:
            return -1
        for dev in self.gpu_devices:
            if dev.idle_memory() >= req:
                return dev.id
        return -1

    def update_task(self, task: TaskInfo) -> None:
        """Reference: UpdateTask, node_info.go:328-340."""
        self.remove_task(task)
        self.add_task(task)

    # ------------------------------------------------------- binding tasks
    def add_binding_task(self, task_uid: str) -> None:
        """Reference: AddBindingTask, node_info.go:429-432."""
        self.binding_tasks.add(task_uid)

    def remove_binding_task(self, task_uid: str) -> None:
        """Reference: RemoveBindingTask, node_info.go:434-437."""
        self.binding_tasks.discard(task_uid)

    # ------------------------------------------------------- state machine
    def sync_state(self) -> None:
        """Recompute the Ready/NotReady state (setNodeState,
        node_info.go:133-170): a node whose accounted usage exceeds its
        declared allocatable is OutOfSync and leaves the schedulable pool
        until the accounts reconcile."""
        if not self.used.less_equal(self.allocatable):
            self.ready = False
            self.state_reason = "OutOfSync"
        elif self.state_reason == "OutOfSync":
            self.ready = True
            self.state_reason = ""

    def clone(self) -> "NodeInfo":
        n = NodeInfo(self.name, self.allocatable.clone(), self.capability.clone(),
                     dict(self.labels), list(self.taints), self.unschedulable,
                     self.ready, self.max_pods)
        for task in self.tasks.values():
            n.add_task(task.clone(), force=True)
        n.binding_tasks = set(self.binding_tasks)
        n.state_reason = self.state_reason
        return n

    def __repr__(self) -> str:
        return f"NodeInfo({self.name}, idle={self.idle}, used={self.used})"
