"""Core cluster objects: Pod and PodGroup.

Reference: k8s core/v1 Pod as consumed by the controllers/scheduler, and
scheduling.volcano.sh/v1beta1 PodGroup
(vendor/.../scheduling/v1beta1/types.go:147-243).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .job_info import Toleration
from .resource import Resource
from .types import DEFAULT_SCHEDULER_NAME, PodGroupPhase

#: annotation linking a pod to its PodGroup (scheduling.k8s.io group-name).
POD_GROUP_ANNOTATION = "scheduling.k8s.io/group-name"
#: annotation carrying the task (role) name on job pods.
TASK_SPEC_ANNOTATION = "volcano.sh/task-spec"
#: label carrying the parent job name.
JOB_NAME_LABEL = "volcano.sh/job-name"


class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)

    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    resources: Dict[str, object] = field(default_factory=dict)  # ResourceList
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    #: node-affinity (NodeSelectorTerm or match-labels dicts) — projected
    #: onto TaskInfo by the scheduler cache
    affinity_required: List = field(default_factory=list)
    affinity_preferred: List = field(default_factory=list)
    priority: int = 0
    restart_policy: str = "OnFailure"
    env: Dict[str, str] = field(default_factory=dict)
    volumes: List[str] = field(default_factory=list)

    phase: str = PodPhase.PENDING
    node_name: str = ""
    gpu_index: int = -1   # assigned shared-GPU card (the GPUIndex
    #                       annotation patch, pod_info.go:154-160)
    exit_code: Optional[int] = None
    deletion_timestamp: Optional[float] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def uid(self) -> str:
        return self.key

    def resreq(self) -> Resource:
        return Resource.from_resource_list(self.resources)

    @property
    def job_name(self) -> str:
        return self.labels.get(JOB_NAME_LABEL, "")

    @property
    def task_role(self) -> str:
        return self.annotations.get(TASK_SPEC_ANNOTATION, "")

    @property
    def pod_group(self) -> str:
        return self.annotations.get(POD_GROUP_ANNOTATION, "")


@dataclass
class PodGroupCondition:
    type: str
    status: str = "True"
    reason: str = ""
    message: str = ""
    transition_time: float = field(default_factory=time.time)


@dataclass
class PodGroup:
    """scheduling.volcano.sh/v1beta1 PodGroup
    (vendor/.../scheduling/v1beta1/types.go:147-243)."""

    name: str
    namespace: str = "default"
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    owner_job: str = ""            # batch Job key that controls this group

    min_member: int = 0
    queue: str = ""
    priority_class_name: str = ""
    min_resources: Dict[str, object] = field(default_factory=dict)

    phase: PodGroupPhase = PodGroupPhase.PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def min_resources_res(self) -> Resource:
        return Resource.from_resource_list(self.min_resources)
