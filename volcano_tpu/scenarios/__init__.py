"""Scheduling-quality observability: scenario engine, quality scorecards,
and the soak-mode CPU-oracle drift watch (ISSUE 9).

CLI: ``python -m volcano_tpu.scenarios --list`` / ``--run NAME [--soak]``.
"""

from __future__ import annotations

from .catalog import SCENARIOS, get_scenario, list_scenarios
from .engine import (DriftCheck, ScenarioResult, oracle_drift_check,
                     run_scenario)
from .quality import (QualityCollector, Scorecard, nearest_rank,
                      publish_quality_gauges, record_result, reset_results,
                      results, share_error, weighted_water_fill)
from .workload import QueueSpec, WorkloadSpec

__all__ = [
    "SCENARIOS", "get_scenario", "list_scenarios",
    "DriftCheck", "ScenarioResult", "oracle_drift_check", "run_scenario",
    "QualityCollector", "Scorecard", "nearest_rank",
    "publish_quality_gauges", "record_result", "reset_results", "results",
    "share_error", "weighted_water_fill",
    "QueueSpec", "WorkloadSpec",
]
