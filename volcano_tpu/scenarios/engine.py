"""Scenario engine: drive a Scheduler over a FakeCluster with a virtual
clock and score what it decides.

The loop shape follows ``chaos/probe.py`` (deterministic virtual
timestamps through ``run_once(now=...)``, per-cycle decision digests,
sha256 fingerprints) but the churn is a trace-shaped workload instead of
a fault storm: seeded arrivals/durations (``workload.py``), diurnal
autoscaler node add/remove, heterogeneous pools, and optional failure
storms reusing the chaos ``FaultPlan``/``FaultInjector``. Observation is
host-only BY CONSTRUCTION — no ops/ changes, no in-graph code — so
decision sha256s are bit-identical with the scenario layer on or off
(``observe=False`` skips every publication and nothing else; pinned by
tests/test_scenarios.py).

Soak mode stretches the horizon and runs continuous CPU-oracle drift
spot-checks: every K cycles two fresh Sessions are built over deep-copy
snapshots of the live cluster and the compiled allocate's decisions must
sha-match ``runtime/cpu_reference.allocate_cpu`` exactly.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import random
from typing import Dict, List, Optional

from .quality import CycleSample, QualityCollector, Scorecard
from .workload import (VT_BASE, WorkloadSpec, arrival_rate_at, build_cluster,
                       build_node, draw_job, node_target_at, poisson)


@dataclasses.dataclass
class DriftCheck:
    """One CPU-oracle spot-check: compiled vs pure-host decisions over the
    same snapshot. ``placed`` is the compiled pass's placement count —
    all-zero decision arrays would compare equal vacuously, so the engine
    runs the check ahead of the cycle (pending arrivals still unplaced)
    and records how much work the comparison actually covered."""

    cycle: int
    compiled_sha: str
    oracle_sha: str
    placed: int = 0

    @property
    def ok(self) -> bool:
        return self.compiled_sha == self.oracle_sha


@dataclasses.dataclass
class ScenarioResult:
    spec: WorkloadSpec
    scorecard: Scorecard
    events: List[dict]
    drift: List[DriftCheck]

    @property
    def ok(self) -> bool:
        return all(d.ok for d in self.drift)


# ----------------------------------------------------------- fingerprints
def _cycle_digest(rec) -> tuple:
    """Decision digest of one cycle (the chaos probe's shape)."""
    return (sorted((b.task_uid, b.node_name, b.gpu_index)
                   for b in rec.binds),
            sorted(e.task_uid for e in rec.evictions),
            sorted(rec.pipelined.items()),
            sorted((u, str(p)) for u, p in rec.phase_updates.items()))


def _sha(payload) -> str:
    return hashlib.sha256(repr(payload).encode()).hexdigest()[:16]


def _decisions_sha(result) -> str:
    import numpy as np
    return hashlib.sha256(
        np.asarray(result.task_node).tobytes()
        + np.asarray(result.task_mode).tobytes()).hexdigest()[:16]


def oracle_drift_check(cluster, conf, now: float, cycle: int) -> DriftCheck:
    """Build two fresh Sessions over deep-copy snapshots of the live
    cluster and compare the compiled allocate's decisions against the
    pure-host CPU oracle, bit for bit. Non-perturbing: both sessions work
    on clones; the live run never sees them. Both sessions run the same
    enqueue pass first so freshly-arrived PodGroups are in allocate scope
    (otherwise the comparison can only cover an empty decision vector)."""
    import numpy as np

    from ..actions import get_action
    from ..framework.session import Session
    compiled = Session(cluster.snapshot(), conf, now=now)
    oracle = Session(cluster.snapshot(), conf, now=now)
    if "enqueue" in conf.actions:
        get_action("enqueue").execute(compiled)
        get_action("enqueue").execute(oracle)
    result = compiled.run_allocate()
    return DriftCheck(cycle=cycle,
                      compiled_sha=_decisions_sha(result),
                      oracle_sha=_decisions_sha(
                          oracle.run_allocate_oracle()),
                      placed=int(np.asarray(result.task_mode > 0).sum()))


# -------------------------------------------------------- initial layouts
def _initial_reclaim_pressure(ci, spec: WorkloadSpec,
                              rng: random.Random) -> Dict[str, int]:
    """Pre-placed pressure so reclaim, reserve, and elect all fire through
    the compiled path from cycle 0:

    - the ``greedy`` queue runs 1-cpu tasks on every node, far over its
      deserved share (the reclaim donor — tests/test_session_e2e.py's
      underserved-queue shape, scaled up);
    - ``starved`` carries pending gangs whose deserved share the donor
      holds (the reclaimers);
    - one high-priority wide job is the elect target; reserve locks nodes
      for it while it stays unready.

    Returns {job uid -> duration} for the engine's completion clock."""
    from ..api import (JobInfo, PodGroupPhase, Resource, TaskInfo,
                       TaskStatus)
    durations: Dict[str, int] = {}
    greedy = JobInfo(uid="default/greedy", name="greedy",
                     namespace="default", queue="greedy", min_available=1,
                     priority=0, creation_timestamp=VT_BASE,
                     pod_group_phase=PodGroupPhase.RUNNING)
    i = 0
    for node in ci.nodes.values():
        per_node = int(node.allocatable.milli_cpu // 1000)
        for _ in range(per_node):
            t = TaskInfo(uid=f"default/greedy-t{i}", name=f"greedy-t{i}",
                         namespace="default",
                         resreq=Resource.from_resource_list({"cpu": "1"}),
                         status=TaskStatus.RUNNING)
            greedy.add_task(t)
            node.add_task(t)
            i += 1
    ci.add_job(greedy)
    durations[greedy.uid] = spec.duration_max
    for j in range(3):
        starv = JobInfo(uid=f"default/starv{j}", name=f"starv{j}",
                        namespace="default", queue="starved",
                        min_available=1, priority=1,
                        creation_timestamp=VT_BASE + j,
                        pod_group_phase=PodGroupPhase.PENDING)
        for t in range(2):
            starv.add_task(TaskInfo(
                uid=f"default/starv{j}-t{t}", name=f"starv{j}-t{t}",
                namespace="default",
                resreq=Resource.from_resource_list({"cpu": "1"})))
        ci.add_job(starv)
        durations[starv.uid] = spec.duration_min + j
    target = JobInfo(uid="default/target", name="target",
                     namespace="default", queue="starved", min_available=1,
                     priority=10, creation_timestamp=VT_BASE,
                     pod_group_phase=PodGroupPhase.PENDING)
    target.add_task(TaskInfo(
        uid="default/target-t0", name="target-t0", namespace="default",
        resreq=Resource.from_resource_list(
            {"cpu": spec.node_cpu})))
    ci.add_job(target)
    durations[target.uid] = spec.duration_min
    return durations


_INITIAL_BUILDERS = {
    "reclaim_pressure": _initial_reclaim_pressure,
}


# --------------------------------------------------------------- the run
class _Run:
    """Mutable state of one scenario run."""

    def __init__(self, spec: WorkloadSpec, seed: int):
        self.spec = spec
        self.seed = seed
        self.rng = random.Random(seed)
        self.events: List[dict] = []
        self.collector = QualityCollector(spec.name, seed)
        self.arrival_cycle: Dict[str, int] = {}   # job uid -> cycle
        self.durations: Dict[str, int] = {}       # job uid -> cycles to run
        self.running_since: Dict[str, int] = {}   # job uid -> cycle
        self.uid_seq = 0
        self.node_seq = 0
        self.digests: List[tuple] = []

    def event(self, cycle: int, kind: str, **fields) -> dict:
        e = dict(sorted(fields.items()))
        e["cycle"] = cycle
        e["kind"] = kind
        self.events.append(e)
        return e


def _complete_jobs(run: _Run, cluster, cycle: int) -> None:
    """Retire jobs whose duration elapsed since they went fully running:
    free their node accounting and remove the job (structural — the
    autoscaler-era cluster genuinely shrinks)."""
    from ..api import TaskStatus
    ci = cluster.ci
    done = []
    for uid in sorted(run.running_since):
        job = ci.jobs.get(uid)
        if job is None:
            run.running_since.pop(uid, None)
            continue
        tasks = list(job.tasks.values())
        if not all(t.status == TaskStatus.RUNNING for t in tasks):
            # evicted back to pending mid-run (reclaim/faults): the run
            # restarts the clock when it becomes fully running again
            run.running_since.pop(uid, None)
            continue
        if cycle - run.running_since[uid] >= run.durations.get(uid, 8):
            done.append(uid)
    for uid in done:
        cluster.remove_job(uid)
        run.running_since.pop(uid, None)
        run.collector.note_completion(cycle)
        run.event(cycle, "complete", job=uid,
                  wait=cycle - run.arrival_cycle.get(uid, 0))


def _inject_arrivals(run: _Run, cluster, cycle: int) -> None:
    n = poisson(run.rng, arrival_rate_at(run.spec, cycle))
    for _ in range(n):
        job, duration = draw_job(run.spec, run.rng, run.uid_seq, cycle)
        run.uid_seq += 1
        cluster.ci.add_job(job)
        cluster.mark_dirty(job_uid=job.uid, structural=True)
        run.arrival_cycle[job.uid] = cycle
        run.durations[job.uid] = duration
        run.collector.note_arrival(cycle)
        run.event(cycle, "arrival", job=job.uid, queue=job.queue,
                  tasks=len(job.tasks), duration=duration)


def _autoscale(run: _Run, cluster, cycle: int) -> None:
    """Track the diurnal node target: add fresh nodes, remove empty ones
    (a real autoscaler drains first; here only task-free nodes leave)."""
    spec = run.spec
    if not spec.autoscale:
        return
    ci = cluster.ci
    target = node_target_at(spec, cycle)
    while len(ci.nodes) < target:
        idx = max(run.node_seq, len(ci.nodes))
        run.node_seq = idx + 1
        node = build_node(spec, idx)
        cluster.add_node(node)
        run.event(cycle, "node_add", node=node.name)
    if len(ci.nodes) > target:
        for name in sorted(ci.nodes, reverse=True):
            if len(ci.nodes) <= target:
                break
            if cluster.remove_node(name):
                run.event(cycle, "node_remove", node=name)


def _advance_bound_tasks(run: _Run, cluster, cycle: int) -> None:
    """Kubelet analog between cycles: Bound -> Running; record when a job
    first becomes fully running (its duration clock starts)."""
    from ..api import TaskStatus
    ci = cluster.ci
    for uid in sorted(t.uid for job in ci.jobs.values()
                      for t in job.tasks.values()
                      if t.status == TaskStatus.BOUND):
        cluster.run_task(uid)
    for uid in sorted(ci.jobs):
        job = ci.jobs[uid]
        tasks = list(job.tasks.values())
        if tasks and uid not in run.running_since \
                and all(t.status == TaskStatus.RUNNING for t in tasks):
            run.running_since[uid] = cycle


def _quality_sample(run: _Run, cluster, cycle: int, binds: int,
                    evictions: int, ssn) -> None:
    from ..api.types import ALLOCATED_STATUSES
    ci = cluster.ci
    capacity = sum(n.allocatable.milli_cpu for n in ci.nodes.values())
    allocated: Dict[str, float] = {}
    demand: Dict[str, float] = {}
    for job in ci.jobs.values():
        for t in job.tasks.values():
            m = t.resreq.milli_cpu
            demand[job.queue] = demand.get(job.queue, 0.0) + m
            if t.status in ALLOCATED_STATUSES:
                allocated[job.queue] = allocated.get(job.queue, 0.0) + m
    weights = {q.name: float(q.weight) for q in ci.queues.values()}
    effects: Dict[str, float] = {}
    actions_tel = (ssn.last_telemetry or {}).get("actions") or {}
    for name, block in actions_tel.items():
        for k, v in block.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                effects[f"{name}_{k}"] = float(v)
            elif v:
                effects.setdefault(f"{name}_count", 0.0)
                effects[f"{name}_count"] += 1.0
    run.collector.add(CycleSample(
        cycle=cycle, capacity_milli_cpu=capacity,
        allocated_milli_cpu=allocated, demand_milli_cpu=demand,
        queue_weights=weights, evictions=evictions, binds=binds,
        action_effects=effects))


def run_scenario(spec: WorkloadSpec, seed: Optional[int] = None,
                 cycles: Optional[int] = None, soak: bool = False,
                 observe: bool = True,
                 drift_check_every: Optional[int] = None,
                 sharded: bool = False) -> ScenarioResult:
    """Run one named scenario end to end and score it.

    ``soak`` stretches the horizon to >= 500 cycles and tightens the
    CPU-oracle drift spot-check interval. ``observe=False`` skips every
    publication (METRICS gauges, the dashboard registry, the JSONL event
    log) and NOTHING else — the on/off decision-sha identity is the
    scenario layer's purity contract. ``sharded`` runs the scheduler on
    the node-axis sharded backend (conf ``sharding: true``); decisions
    must sha-match the unsharded run (tests/test_checkpoint.py pins
    trace-replay). ``spec.restart_every`` (when > 0) kills the scheduler
    every N cycles and restores a fresh one from its crash-consistent
    checkpoint — the restart-storm scenario. ``spec.failover_every``
    (when > 0) serves the run from an HA replica pair instead: the
    leader streams checkpoint envelopes to a warm standby every cycle,
    and every N cycles it is killed and the standby promoted behind the
    lease-generation fence — the failover-storm scenario (decision-
    neutral like restarts: truth is the external cluster)."""
    import os
    import tempfile

    from ..chaos.inject import FaultInjector, chaos
    from ..chaos.plan import FaultPlan
    from ..framework.conf import parse_conf
    from ..runtime.fake_cluster import FakeCluster
    from ..runtime.scheduler import Scheduler
    from ..telemetry import spans

    seed = spec.seed if seed is None else seed
    cycles = spec.cycles if cycles is None else cycles
    if soak:
        cycles = max(cycles, 500)
    every = drift_check_every if drift_check_every is not None \
        else (min(spec.drift_check_every, 50) if soak
              else spec.drift_check_every)

    run = _Run(spec, seed)
    ci = build_cluster(spec)
    run.node_seq = spec.n_nodes
    if spec.initial:
        durations = _INITIAL_BUILDERS[spec.initial](ci, spec, run.rng)
        run.durations.update(durations)
        for uid in durations:
            run.arrival_cycle[uid] = 0
            run.collector.note_arrival(0)
    cluster = FakeCluster(ci)
    conf = parse_conf(("sharding: true\n" if sharded else "") + spec.conf)
    elector = sender = standby = None
    fo_clock = None
    standby_n = 0
    if spec.failover_every > 0:
        from ..runtime.leader import (DEFAULT_LEASE_DURATION,
                                      LeaderElector)
        from ..runtime.replication import replica_pair
        from ..runtime.system import VolcanoSystem

        class _FoClock:  # fake monotonic clock, like chaos/failover.py
            now = 100.0

            def __call__(self):
                return self.now

        fo_clock = _FoClock()
        fo_api = VolcanoSystem().api
        elector = LeaderElector(fo_api, identity="leader-0",
                                clock=fo_clock)
        elector.tick()
    sched = Scheduler(cluster, conf=conf, pipeline=False, elector=elector)
    if spec.failover_every > 0:
        sender, standby = replica_pair(sched, conf)

    injector = None
    if spec.fault_kinds:
        plan = FaultPlan(seed=seed, cycles=cycles, kinds=spec.fault_kinds,
                         per_kind=spec.faults_per_kind)
        injector = FaultInjector(plan)
    drift: List[DriftCheck] = []
    ckpt_dir = ckpt_path = None
    if spec.restart_every > 0:
        ckpt_dir = tempfile.TemporaryDirectory(prefix="vckp-scenario-")
        ckpt_path = os.path.join(ckpt_dir.name, "sched.vckp")
    ctx = chaos(injector) if injector is not None \
        else contextlib.nullcontext()
    with ctx:
        for c in range(cycles):
            vt = VT_BASE + c
            _complete_jobs(run, cluster, c)
            _inject_arrivals(run, cluster, c)
            _autoscale(run, cluster, c)
            if ckpt_path and c and c % spec.restart_every == 0:
                # the restart storm: the scheduler "process" dies between
                # cycles and a fresh one restores from the last checkpoint
                # (decision-neutral — truth is the external cluster)
                sched = Scheduler(cluster, conf=conf, pipeline=False)
                outcome = sched.restore(ckpt_path, now=vt)
                run.event(c, "restart", outcome=outcome)
                if observe:
                    spans.log_event("scenario_restart", scenario=spec.name,
                                    seed=seed, cycle=c, outcome=outcome)
            if fo_clock is not None:
                fo_clock.now += 1.0
            if standby is not None and c and c % spec.failover_every == 0:
                # the failover storm: the leader dies between cycles; its
                # lease expires and the warm standby promotes behind a
                # fresh fence generation (decision-neutral, like restarts)
                fo_clock.now += DEFAULT_LEASE_DURATION + 1.0
                standby_n += 1
                el = LeaderElector(fo_api,
                                   identity=f"standby-{standby_n}",
                                   clock=fo_clock)
                sched = standby.promote(cluster, conf=conf,
                                        pipeline=False, now=vt,
                                        elector=el)
                outcome = standby.last_outcome
                run.event(c, "failover", outcome=outcome,
                          generation=el.generation)
                sender, standby = replica_pair(sched, conf)
                if observe:
                    spans.log_event("scenario_failover",
                                    scenario=spec.name, seed=seed,
                                    cycle=c, outcome=outcome,
                                    generation=el.generation)
            if every and c and c % every == 0:
                # spot-check BEFORE the cycle: this cycle's arrivals are
                # still pending, so the compared decision vector carries
                # real placements, not the post-cycle empty remainder
                check = oracle_drift_check(cluster, conf, vt, c)
                drift.append(check)
                run.event(c, "drift_check", ok=check.ok,
                          placed=check.placed,
                          compiled_sha=check.compiled_sha,
                          oracle_sha=check.oracle_sha)
            binds0 = len(cluster.binds)
            evicts0 = len(cluster.evictions)
            ssn = sched.run_once(now=vt)
            run.digests.append(_cycle_digest(ssn))
            new_binds = cluster.binds[binds0:]
            for task_uid, _node in new_binds:
                job_uid = task_uid.rsplit("-t", 1)[0]
                if job_uid in run.arrival_cycle:
                    run.collector.note_wait(c - run.arrival_cycle[job_uid])
            evictions = len(cluster.evictions) - evicts0
            _quality_sample(run, cluster, c, len(new_binds), evictions, ssn)
            _advance_bound_tasks(run, cluster, c)
            if ckpt_path:
                sched.checkpoint(ckpt_path, now=vt)
            if sender is not None:
                sender.stream()
            if observe:
                spans.log_event("scenario_cycle", scenario=spec.name,
                                seed=seed, cycle=c, binds=len(new_binds),
                                evictions=evictions,
                                jobs=len(cluster.ci.jobs),
                                nodes=len(cluster.ci.nodes))

    if ckpt_dir is not None:
        ckpt_dir.cleanup()
    card = run.collector.scorecard(cycles)
    card.event_sha = _sha(run.events)
    card.decisions_sha = _sha(run.digests)
    card.drift_checks = len(drift)
    card.drift_failures = sum(1 for d in drift if not d.ok)
    card.faults_fired = len(injector.fired) if injector is not None else 0
    if observe:
        from .quality import publish_quality_gauges, record_result
        publish_quality_gauges(card)
        record_result(card)
        spans.log_event("scenario_done", scenario=spec.name, seed=seed,
                        cycles=cycles, event_sha=card.event_sha,
                        decisions_sha=card.decisions_sha,
                        drift_failures=card.drift_failures,
                        drf_share_error=card.drf_share_error,
                        makespan_cycles=card.makespan_cycles)
    return ScenarioResult(spec=spec, scorecard=card, events=run.events,
                          drift=drift)
