"""Scheduling-quality scorecard math and the scenario results registry.

Every function here is pure host arithmetic over plain Python values —
deliberately hand-computable so tests can pin exact numbers (ISSUE 9
satellite: exact makespan, exact DRF share error including the
zero-deserved queue edge case, exact wait-time quantiles). The scenario
engine feeds it per-cycle samples; the output is one :class:`Scorecard`
per run, published three ways with the SAME numbers:

- ``volcano_quality_*`` gauges on the process-global METRICS registry
  (the /metrics exposition),
- the bounded module-level results registry the dashboard serves as the
  ``scenarios`` table / ``/api/scenarios``,
- the bench ``scenarios`` block (bench.py, fail-soft).
"""

from __future__ import annotations

import dataclasses
import math
import threading
from collections import deque
from typing import Dict, List, Mapping, Optional

#: quantiles every wait-time surface reports, in order
WAIT_QUANTILES = (50, 95, 99)


# ------------------------------------------------------------- primitives
def nearest_rank(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank quantile (the textbook definition: the smallest value
    with at least ``q``% of the sample at or below it). Exact on tiny
    fixtures — no interpolation, so hand computation matches to the bit."""
    if not values:
        return None
    if not 0 < q <= 100:
        raise ValueError(f"quantile out of range: {q}")
    ordered = sorted(values)
    rank = math.ceil(q / 100.0 * len(ordered))
    return ordered[rank - 1]


def weighted_water_fill(capacity: float, weights: Mapping[str, float],
                        demands: Mapping[str, float]) -> Dict[str, float]:
    """Weight-proportional deserved shares capped by demand — the host-side
    mirror of the proportion plugin's water-filling (proportion.go:213-240,
    ops/fairshare.proportion_deserved), reduced to the scorecard's single
    dominant dimension. A queue with zero weight or zero demand deserves
    exactly 0 (the zero-deserved edge case the DRF error must still score:
    anything it holds is pure error)."""
    deserved = {q: 0.0 for q in demands}
    active = {q for q in demands
              if demands[q] > 0 and weights.get(q, 0) > 0}
    remaining = float(capacity)
    while active and remaining > 1e-9:
        total_w = sum(weights[q] for q in active)
        share = {q: remaining * weights[q] / total_w for q in active}
        saturated = {q for q in active
                     if deserved[q] + share[q] >= demands[q] - 1e-9}
        if not saturated:
            for q in active:
                deserved[q] += share[q]
            break
        for q in saturated:
            remaining -= demands[q] - deserved[q]
            deserved[q] = demands[q]
        active -= saturated
    return deserved


def share_error(allocated: Mapping[str, float],
                deserved: Mapping[str, float],
                capacity: float) -> float:
    """DRF share error for one cycle: total absolute deviation between the
    allocation each queue holds and the share it deserves, normalized by
    cluster capacity (so 0 = perfectly fair, and an entire cluster held by
    a zero-deserved queue scores 1 on that queue alone)."""
    if capacity <= 0:
        return 0.0
    keys = set(allocated) | set(deserved)
    return sum(abs(allocated.get(q, 0.0) - deserved.get(q, 0.0))
               for q in keys) / float(capacity)


# ------------------------------------------------------------- collector
@dataclasses.dataclass
class CycleSample:
    """What the engine observes after one scheduling cycle (virtual time)."""

    cycle: int
    capacity_milli_cpu: float
    allocated_milli_cpu: Dict[str, float]    # per queue
    demand_milli_cpu: Dict[str, float]       # per queue (unfinished work)
    queue_weights: Dict[str, float]
    evictions: int = 0
    binds: int = 0
    action_effects: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Scorecard:
    """One scenario run's quality scorecard — plain JSON-safe values."""

    scenario: str
    seed: int
    cycles: int
    jobs_submitted: int = 0
    jobs_completed: int = 0
    tasks_bound: int = 0
    #: virtual cycles from first arrival to last job completion (None
    #: until at least one job completed)
    makespan_cycles: Optional[int] = None
    drf_share_error: Optional[float] = None       # mean over cycles
    drf_share_error_max: Optional[float] = None
    preemption_churn_total: int = 0
    node_utilization: Optional[float] = None      # mean over cycles
    wait_cycles: Dict[str, Optional[float]] = dataclasses.field(
        default_factory=dict)                      # {"p50": ..., ...}
    action_effects: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    event_sha: Optional[str] = None
    decisions_sha: Optional[str] = None
    drift_checks: int = 0
    drift_failures: int = 0
    faults_fired: int = 0
    #: fleet tenant the run scored (None for single-cluster runs — the
    #: dashboard's scenarios table shows "-" and the quality gauges
    #: carry no tenant label, so pre-fleet surfaces are unchanged)
    tenant: Optional[str] = None

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def complete(self) -> bool:
        """A full scorecard: every headline metric non-null (the tier-1
        smoke's acceptance predicate)."""
        return (self.drf_share_error is not None
                and self.node_utilization is not None
                and self.makespan_cycles is not None
                and all(self.wait_cycles.get(f"p{q}") is not None
                        for q in WAIT_QUANTILES))


class QualityCollector:
    """Accumulates per-cycle samples + lifecycle marks into a Scorecard."""

    def __init__(self, scenario: str, seed: int,
                 tenant: Optional[str] = None):
        self.scenario = scenario
        self.seed = seed
        self.tenant = tenant
        self.samples: List[CycleSample] = []
        self._first_arrival: Optional[int] = None
        self._last_completion: Optional[int] = None
        self.jobs_submitted = 0
        self.jobs_completed = 0
        self.tasks_bound = 0
        self.wait_samples: List[float] = []
        self.action_effects: Dict[str, float] = {}

    # lifecycle marks, all in virtual cycles -----------------------------
    def note_arrival(self, cycle: int, jobs: int = 1) -> None:
        self.jobs_submitted += jobs
        if self._first_arrival is None:
            self._first_arrival = cycle

    def note_completion(self, cycle: int, jobs: int = 1) -> None:
        self.jobs_completed += jobs
        self._last_completion = cycle

    def note_wait(self, wait_cycles: float) -> None:
        self.wait_samples.append(float(wait_cycles))

    def add(self, sample: CycleSample) -> None:
        self.samples.append(sample)
        self.tasks_bound += sample.binds
        for k, v in sample.action_effects.items():
            if k.endswith("_total"):
                # running-total effects (e.g. reserve's locked_total):
                # the peak is the meaningful scorecard number, not a sum
                # of per-cycle totals
                self.action_effects[k] = max(
                    self.action_effects.get(k, 0.0), v)
            else:
                self.action_effects[k] = self.action_effects.get(k, 0.0) + v

    # readout ------------------------------------------------------------
    def scorecard(self, cycles: int) -> Scorecard:
        card = Scorecard(scenario=self.scenario, seed=self.seed,
                         tenant=self.tenant, cycles=cycles,
                         jobs_submitted=self.jobs_submitted,
                         jobs_completed=self.jobs_completed,
                         tasks_bound=self.tasks_bound,
                         preemption_churn_total=sum(
                             s.evictions for s in self.samples),
                         action_effects={k: round(v, 3) for k, v in
                                         sorted(self.action_effects.items())})
        if self._first_arrival is not None \
                and self._last_completion is not None:
            card.makespan_cycles = self._last_completion - self._first_arrival
        if self.samples:
            errors = []
            utils = []
            for s in self.samples:
                deserved = weighted_water_fill(
                    s.capacity_milli_cpu, s.queue_weights,
                    s.demand_milli_cpu)
                errors.append(share_error(s.allocated_milli_cpu, deserved,
                                          s.capacity_milli_cpu))
                if s.capacity_milli_cpu > 0:
                    utils.append(sum(s.allocated_milli_cpu.values())
                                 / s.capacity_milli_cpu)
            card.drf_share_error = round(sum(errors) / len(errors), 6)
            card.drf_share_error_max = round(max(errors), 6)
            if utils:
                card.node_utilization = round(sum(utils) / len(utils), 6)
        card.wait_cycles = {
            f"p{q}": nearest_rank(self.wait_samples, q)
            for q in WAIT_QUANTILES}
        return card


# ---------------------------------------------------- results + /metrics
_LOCK = threading.Lock()
_RESULTS: deque = deque(maxlen=32)


def record_result(card: Scorecard) -> None:
    """Keep the run's scorecard in the bounded registry the dashboard's
    ``scenarios`` table and ``/api/scenarios`` serve."""
    with _LOCK:
        _RESULTS.append(card.to_dict())


def results() -> List[Dict[str, object]]:
    with _LOCK:
        return [dict(r) for r in _RESULTS]


def reset_results() -> None:
    with _LOCK:
        _RESULTS.clear()


def publish_quality_gauges(card: Scorecard, registry=None) -> None:
    """Mirror the scorecard onto ``volcano_quality_*`` gauges — the same
    numbers /api/scenarios serves, on the cumulative /metrics surface."""
    if registry is None:
        from ..metrics import METRICS as registry
    labels = {"scenario": card.scenario}
    if card.tenant:
        labels["tenant"] = card.tenant
    g = registry.set_gauge
    if card.makespan_cycles is not None:
        g("quality_makespan_cycles", labels, card.makespan_cycles)
    if card.drf_share_error is not None:
        g("quality_drf_share_error", labels, card.drf_share_error)
    if card.node_utilization is not None:
        g("quality_node_utilization", labels, card.node_utilization)
    g("quality_preemption_churn_total", labels,
      card.preemption_churn_total)
    g("quality_jobs_completed", labels, card.jobs_completed)
    g("quality_drift_failures", labels, card.drift_failures)
    for q in WAIT_QUANTILES:
        v = card.wait_cycles.get(f"p{q}")
        if v is not None:
            g("quality_queue_wait_cycles",
              {"scenario": card.scenario, "quantile": f"p{q}"}, v)
