"""Seed-deterministic workload generation for quality scenarios.

Shapes follow the published cluster traces the batch-scheduling literature
benchmarks against (Google ClusterData 2019, Alibaba cluster-trace-v2018):
bursty arrivals (Poisson base rate with diurnal modulation), heavy-tailed
job durations (bounded Pareto), small gang sizes with a fat tail, and a
mix of narrow/wide resource requests. Everything derives from ONE private
``random.Random(seed)`` — the same discipline as chaos ``FaultPlan`` — so
a scenario's event stream and scorecard are bit-reproducible from its
seed (tests/test_scenarios.py pins this).

No wall clock anywhere: time is the engine's virtual cycle counter.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

#: virtual-time origin — nonzero so JobInfo's ``creation_timestamp or
#: time.time()`` fallback can never smuggle wall time into the run
VT_BASE = 1000.0

#: gang sizes with a fat tail (trace-shaped: mostly small, few wide)
_GANG_SIZES = (1, 1, 2, 2, 3, 4, 6, 8)

#: per-task cpu requests in millicores (narrow-heavy mix)
_TASK_CPU_M = (500, 1000, 1000, 2000, 2000, 4000)


@dataclasses.dataclass(frozen=True)
class QueueSpec:
    name: str
    weight: int = 1
    reclaimable: bool = True


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Declarative scenario shape; ``catalog.py`` holds the named ones."""

    name: str
    description: str
    conf: str                      # scheduler YAML conf for the run
    cycles: int = 64               # default horizon (CLI/soak override)
    seed: int = 0
    n_nodes: int = 6
    node_cpu: str = "8"
    node_mem: str = "16Gi"
    queues: Tuple[QueueSpec, ...] = (QueueSpec("default", 1),)
    #: mean arrivals per cycle (Poisson base rate; 0 = closed workload)
    arrival_rate: float = 0.6
    #: diurnal modulation amplitude in [0, 1) over ``diurnal_period``
    diurnal_amplitude: float = 0.0
    diurnal_period: int = 48
    #: autoscaler node churn: track the diurnal curve between bounds
    autoscale: bool = False
    min_nodes: int = 4
    max_nodes: int = 10
    #: heterogeneous pool: every third node carries shared-GPU cards and
    #: a TDM revocable-zone window (gpu-sharing + tdm together)
    hetero: bool = False
    #: failure storm composed from the chaos FaultPlan (empty = no faults)
    fault_kinds: Tuple[str, ...] = ()
    faults_per_kind: int = 1
    #: bounded-Pareto duration parameters, in cycles
    duration_min: int = 4
    duration_max: int = 40
    duration_alpha: float = 1.5
    #: name of a builder in engine._INITIAL_BUILDERS seeding the cluster
    #: with pre-placed work (the reclaim-pressure setup)
    initial: Optional[str] = None
    #: restart storm: every N cycles the scheduler process "dies" and a
    #: fresh one restores from its crash-consistent checkpoint
    #: (runtime/checkpoint.py); 0 = never
    restart_every: int = 0
    #: failover storm: the run is served by an HA replica pair
    #: (runtime/replication.py) and every N cycles the leader is killed
    #: and the warm standby promoted behind a lease-generation fence;
    #: 0 = single replica, no HA wiring at all
    failover_every: int = 0
    #: CPU-oracle drift spot-check interval (cycles); soak may tighten
    drift_check_every: int = 16


# ------------------------------------------------------------ generators
def poisson(rng: random.Random, lam: float) -> int:
    """Knuth's algorithm off the scenario's private Random — deterministic
    per (seed, draw index), unlike numpy's global state."""
    if lam <= 0:
        return 0
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def arrival_rate_at(spec: WorkloadSpec, cycle: int) -> float:
    """Diurnal modulation of the base Poisson rate."""
    if spec.diurnal_amplitude <= 0:
        return spec.arrival_rate
    phase = 2.0 * math.pi * cycle / max(spec.diurnal_period, 1)
    return spec.arrival_rate * (1.0 + spec.diurnal_amplitude
                                * math.sin(phase))


def node_target_at(spec: WorkloadSpec, cycle: int) -> int:
    """Autoscaler target node count: tracks the diurnal load curve between
    ``min_nodes`` and ``max_nodes`` (the add/remove churn source)."""
    if not spec.autoscale:
        return spec.n_nodes
    phase = 2.0 * math.pi * cycle / max(spec.diurnal_period, 1)
    frac = 0.5 * (1.0 + math.sin(phase))
    return spec.min_nodes + int(round(
        frac * (spec.max_nodes - spec.min_nodes)))


def draw_duration(spec: WorkloadSpec, rng: random.Random) -> int:
    """Bounded Pareto in cycles — the heavy-tailed duration mix."""
    d = spec.duration_min * rng.paretovariate(spec.duration_alpha)
    return int(min(max(d, spec.duration_min), spec.duration_max))


def draw_job(spec: WorkloadSpec, rng: random.Random, uid_seq: int,
             cycle: int):
    """One arriving job (PodGroup phase Pending: the enqueue action must
    admit it, like a freshly created PodGroup)."""
    from ..api import JobInfo, PodGroupPhase, Resource, TaskInfo
    queue = spec.queues[rng.randrange(len(spec.queues))].name
    gang = rng.choice(_GANG_SIZES)
    cpu_m = rng.choice(_TASK_CPU_M)
    uid = f"default/s{uid_seq}"
    job = JobInfo(uid=uid, name=f"s{uid_seq}", namespace="default",
                  queue=queue, min_available=max(1, gang // 2),
                  priority=rng.randrange(3),
                  creation_timestamp=VT_BASE + cycle,
                  pod_group_phase=PodGroupPhase.PENDING)
    rl: Dict[str, str] = {"cpu": f"{cpu_m}m", "memory": "1Gi"}
    for t in range(gang):
        job.add_task(TaskInfo(
            uid=f"{uid}-t{t}", name=f"s{uid_seq}-t{t}",
            namespace="default",
            resreq=Resource.from_resource_list(dict(rl))))
    return job, draw_duration(spec, rng)


def build_node(spec: WorkloadSpec, index: int):
    """One cluster node; in hetero mode every third node is a shared-GPU
    node carrying a TDM revocable-zone window (both plugin families in one
    pool)."""
    from ..api import NodeInfo, Resource
    rl = {"cpu": spec.node_cpu, "memory": spec.node_mem, "pods": "110"}
    labels: Dict[str, str] = {}
    if spec.hetero and index % 3 == 2:
        from ..api import GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE
        from ..plugins.tdm import REVOCABLE_ZONE_LABEL
        rl[GPU_MEMORY_RESOURCE] = "16"
        rl[GPU_NUMBER_RESOURCE] = "2"
        labels[REVOCABLE_ZONE_LABEL] = "z1"
        labels["pool"] = "accel"
    else:
        labels["pool"] = "general"
    return NodeInfo(f"n{index}",
                    allocatable=Resource.from_resource_list(rl),
                    labels=labels)


def build_cluster(spec: WorkloadSpec):
    """The scenario's starting ClusterInfo: nodes + queues, no jobs (the
    ``initial`` builder, when named, seeds pre-placed work afterwards)."""
    from ..api import ClusterInfo, QueueInfo
    ci = ClusterInfo()
    for i in range(spec.n_nodes):
        ci.add_node(build_node(spec, i))
    for q in spec.queues:
        ci.add_queue(QueueInfo(q.name, weight=q.weight,
                               reclaimable=q.reclaimable))
    return ci
