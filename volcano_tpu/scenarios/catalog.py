"""The named scenario catalog.

Each entry is a :class:`~.workload.WorkloadSpec`; ``run_scenario`` turns a
name into a scored run. Conf strings keep ``allocate`` as the cycle's last
action (the pipeline-compatible shape every preset pins) and drive the
compiled path — the scenario layer itself never touches ops/.
"""

from __future__ import annotations

from typing import Dict, List

from .workload import QueueSpec, WorkloadSpec

_BASE_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

#: gpu-sharing + TDM revocable zones together (the hetero pool); the
#: window spans the whole virtual day so placement stays deterministic
_HETERO_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: proportion
  - name: binpack
  - name: tdm
    arguments:
      tdm.revocable-zone.z1: "00:00-23:59"
"""

#: reclaim + reserve + elect all through the compiled path: reclaim runs
#: the compiled preempt cycle (mode="reclaim"); elect/reserve feed the
#: compiled allocate via AllocateExtras.target_job / node_locked
_RECLAIM_CONF = """
actions: "enqueue, elect, reserve, reclaim, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: proportion
  - name: predicates
  - name: nodeorder
  - name: reservation
"""

SCENARIOS: Dict[str, WorkloadSpec] = {}


def _register(spec: WorkloadSpec) -> WorkloadSpec:
    SCENARIOS[spec.name] = spec
    return spec


_register(WorkloadSpec(
    name="trace-replay",
    description="Trace-shaped open workload: Poisson arrivals, "
                "heavy-tailed durations, two weighted queues — the "
                "baseline quality scorecard (and the tier-1 smoke).",
    conf=_BASE_CONF,
    cycles=48,
    n_nodes=6,
    queues=(QueueSpec("batch", 1), QueueSpec("svc", 2)),
    arrival_rate=0.8,
    drift_check_every=16,
))

_register(WorkloadSpec(
    name="diurnal-churn",
    description="Diurnal load curve with autoscaler node add/remove "
                "churn tracking it (structural epochs every swing).",
    conf=_BASE_CONF,
    cycles=96,
    n_nodes=6,
    queues=(QueueSpec("batch", 1), QueueSpec("svc", 2)),
    arrival_rate=0.7,
    diurnal_amplitude=0.8,
    diurnal_period=32,
    autoscale=True,
    min_nodes=4,
    max_nodes=9,
    drift_check_every=24,
))

_register(WorkloadSpec(
    name="hetero-pools",
    description="Heterogeneous pool: shared-GPU nodes carrying TDM "
                "revocable-zone windows next to general nodes, one "
                "cluster, both plugin families live.",
    conf=_HETERO_CONF,
    cycles=48,
    n_nodes=6,
    hetero=True,
    queues=(QueueSpec("batch", 1), QueueSpec("svc", 2)),
    arrival_rate=0.7,
    drift_check_every=16,
))

_register(WorkloadSpec(
    name="failure-storm",
    description="Trace-shaped load under a seeded chaos FaultPlan storm "
                "of every recoverable kind — quality under recovery, "
                "decisions still oracle-clean.",
    conf=_BASE_CONF,
    cycles=48,
    n_nodes=6,
    queues=(QueueSpec("batch", 1), QueueSpec("svc", 2)),
    arrival_rate=0.6,
    fault_kinds=("backend_loss", "resident_corrupt", "mirror_drift",
                 "bind_fail", "evict_fail"),
    faults_per_kind=1,
    drift_check_every=16,
))

_register(WorkloadSpec(
    name="restart-storm",
    description="Trace-shaped load under a restart storm: the scheduler "
                "dies every few cycles and warm-restarts from its "
                "crash-consistent checkpoint; decisions and the "
                "scorecard must survive every restart.",
    conf=_BASE_CONF,
    cycles=48,
    n_nodes=6,
    queues=(QueueSpec("batch", 1), QueueSpec("svc", 2)),
    arrival_rate=0.7,
    restart_every=6,
    drift_check_every=16,
))

_register(WorkloadSpec(
    name="failover-storm",
    description="Trace-shaped load served by an HA replica pair under "
                "repeated leader kills: every few cycles the leader "
                "dies, its lease expires, and the warm standby promotes "
                "behind a fresh fence generation; decisions and the "
                "scorecard must survive every handoff (storm sha == "
                "calm sha).",
    conf=_BASE_CONF,
    cycles=48,
    n_nodes=6,
    queues=(QueueSpec("batch", 1), QueueSpec("svc", 2)),
    arrival_rate=0.7,
    failover_every=6,
    drift_check_every=16,
))

_register(WorkloadSpec(
    name="reclaim-pressure",
    description="Over-served greedy queue vs starving weighted queue "
                "plus a wide high-priority target: reclaim, reserve, "
                "and elect all fire through the compiled path with "
                "effects in the scorecard.",
    conf=_RECLAIM_CONF,
    cycles=32,
    n_nodes=4,
    node_cpu="8",
    queues=(QueueSpec("greedy", 1, reclaimable=True),
            QueueSpec("starved", 4)),
    arrival_rate=0.0,
    initial="reclaim_pressure",
    duration_min=6,
    duration_max=64,
    drift_check_every=8,
))


def list_scenarios() -> List[WorkloadSpec]:
    return [SCENARIOS[k] for k in sorted(SCENARIOS)]


def get_scenario(name: str) -> WorkloadSpec:
    try:
        return SCENARIOS[name]
    except KeyError:
        known = ", ".join(sorted(SCENARIOS))
        raise KeyError(f"unknown scenario {name!r} (known: {known})") \
            from None
