"""``python -m volcano_tpu.scenarios`` — run quality scenarios.

``--list`` prints the catalog; ``--run NAME`` runs one scenario and prints
its scorecard as JSON (bit-reproducible from ``--seed``); ``--soak``
stretches the horizon to >= 500 cycles with continuous CPU-oracle drift
spot-checks. ``--smoke`` is the tier-1 gate: a short trace-replay run must
produce a COMPLETE scorecard (non-null headline metrics) and pass its
oracle drift spot-check.

Exit 0 on success, 1 on a failed claim (drift mismatch / incomplete smoke
scorecard), 2 on harness error.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="scheduling-quality scenarios: trace replay, "
                    "scorecards, soak-mode drift watch")
    parser.add_argument("--list", action="store_true",
                        help="list the scenario catalog")
    parser.add_argument("--run", metavar="NAME",
                        help="run one named scenario")
    parser.add_argument("--seed", type=int, default=None,
                        help="override the scenario's seed")
    parser.add_argument("--cycles", type=int, default=None,
                        help="override the scenario's horizon")
    parser.add_argument("--soak", action="store_true",
                        help="long-horizon soak (>= 500 cycles) with "
                             "continuous oracle drift spot-checks")
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1 smoke: short trace-replay run, "
                             "complete scorecard + drift check required")
    parser.add_argument("--sharded", action="store_true",
                        help="run on the node-axis sharded backend (conf "
                             "sharding: true); decisions must sha-match "
                             "the unsharded run")
    parser.add_argument("--events", action="store_true",
                        help="include the full event stream in the JSON")
    args = parser.parse_args(argv)

    from . import get_scenario, list_scenarios, run_scenario
    if args.list:
        for spec in list_scenarios():
            print(f"{spec.name:18s} {spec.description}")
        return 0
    if args.smoke:
        # every=4 lands checks both while the cluster is filling and once
        # it is saturated, so at least one check scores real placements
        name, cycles, every = "trace-replay", args.cycles or 16, 4
    elif args.run:
        name, cycles, every = args.run, args.cycles, None
    else:
        parser.print_usage()
        return 2
    try:
        spec = get_scenario(name)
        result = run_scenario(spec, seed=args.seed, cycles=cycles,
                              soak=args.soak, drift_check_every=every,
                              sharded=args.sharded)
    except KeyError as e:
        print(str(e), file=sys.stderr)
        return 2
    except Exception as e:  # harness failure, not a quality verdict
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 2
    out = {"scenario": spec.name, "scorecard": result.scorecard.to_dict(),
           "drift": [{"cycle": d.cycle, "ok": d.ok, "placed": d.placed,
                      "compiled_sha": d.compiled_sha,
                      "oracle_sha": d.oracle_sha} for d in result.drift]}
    if args.events:
        out["events"] = result.events
    print(json.dumps(out, indent=2, default=str))
    ok = result.ok
    if args.smoke or args.soak:
        ok = ok and result.drift and result.scorecard.complete()
        if args.smoke and not result.scorecard.complete():
            print("scenario smoke FAILED: incomplete scorecard "
                  "(a headline metric is null)", file=sys.stderr)
        if args.smoke and not any(d.placed for d in result.drift):
            ok = False
            print("scenario smoke FAILED: every drift check was vacuous "
                  "(no placements compared)", file=sys.stderr)
    if not result.ok:
        print("scenario FAILED: CPU-oracle drift detected "
              "(compiled decisions diverged)", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
