#!/usr/bin/env bash
# Tier-1 verify — the EXACT command from ROADMAP.md, wrapped so the
# builder, CI, and the driver all run the identical thing.
#
# Fast deterministic subset: excludes tests marked `slow` (registered in
# tests/conftest.py; run `pytest -m slow` for the long tail — sharded
# 8-device identity, full hdrf outcome sweeps, sidecar serving e2e).
# DOTS_PASSED counts progress dots so a timeout mid-run still reports how
# far the suite got.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
