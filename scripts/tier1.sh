#!/usr/bin/env bash
# Tier-1 verify — the EXACT pytest command from ROADMAP.md, wrapped so the
# builder, CI, and the driver all run the identical thing, followed by the
# graphcheck static-analysis gate (scripts/graphcheck.sh --fast — every
# family in analysis.FAMILIES, incl. the telemetry, donation,
# sharded-collective, cost-model, and metrics-hygiene contracts; skip
# with TIER1_SKIP_GRAPHCHECK=1).
#
# Fast deterministic subset: excludes tests marked `slow` (registered in
# tests/conftest.py; run `pytest -m slow` for the long tail — sharded
# 8-device identity, full hdrf outcome sweeps, sidecar serving e2e, the
# full-entry graphcheck CLI run). DOTS_PASSED counts progress dots so a
# timeout mid-run still reports how far the suite got.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
# 1500s wall cap: recalibrated for the current 1-vCPU CI box (the suite
# passes in ~1130s there; the previous 870s cap dated from a faster host)
timeout -k 10 1500 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
grc=0
if [ "${TIER1_SKIP_GRAPHCHECK:-0}" != "1" ]; then
    # the fast pruned entry set; tests/test_graphcheck.py already ran the
    # same pass in-suite — this standalone run hands harnesses the JSON
    # report + stable exit code without parsing pytest output
    bash scripts/graphcheck.sh --fast || grc=$?
fi
trc=0
if [ "${TIER1_SKIP_TRACE:-0}" != "1" ]; then
    # span-trace smoke (volcano_tpu/telemetry/spans): a short pipelined
    # loop must export Chrome trace-event JSON that parses, and its
    # pipeline-occupancy analysis must show nonzero host/device overlap
    # (the sync loop's window is ~all blocked readback; the pipelined
    # loop's ingest work overlaps the in-flight device window)
    env JAX_PLATFORMS=cpu python -m volcano_tpu.telemetry \
        --trace /tmp/_t1_trace.json --cycles 12 \
        > /tmp/_t1_trace_summary.json || trc=$?
    if [ $trc -eq 0 ]; then
        python scripts/trace_check.py /tmp/_t1_trace.json \
            /tmp/_t1_trace_summary.json || trc=$?
    fi
fi
crc=0
if [ "${TIER1_SKIP_CHAOS:-0}" != "1" ]; then
    # fast chaos smoke (volcano_tpu/chaos): a seeded storm of every
    # recoverable fault kind over a multi-cycle pipelined run, verified
    # decision-sha-identical to the clean run, with the planted
    # resident-state corruption provably tripping the integrity digest
    env JAX_PLATFORMS=cpu python -m volcano_tpu.chaos --smoke || crc=$?
    # the same storm with the node-axis sharded backend (ISSUE 7): fault
    # recovery and digest discipline must hold per-shard too
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
        python -m volcano_tpu.chaos --smoke --sharded || crc=$?
    # and with the shard-local pallas candidate launch (ISSUE 14): digest
    # trips + recoveries on the 8-device mesh, decisions equal clean
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
        python -m volcano_tpu.chaos --smoke --sharded --pallas-interpret \
        || crc=$?
    # and on the wavefront placement path (ISSUE 16, wave_width > 1):
    # faults land mid-wave, the digest still trips, and the
    # order-preserving commit rule keeps decisions equal to the clean run
    env JAX_PLATFORMS=cpu python -m volcano_tpu.chaos --smoke --wave 4 \
        || crc=$?
fi
src=0
if [ "${TIER1_SKIP_SPEC:-0}" != "1" ]; then
    # speculation smoke (volcano_tpu/chaos/spec): the depth-k sha-matrix —
    # sync vs depth-1 vs depth-k decision streams over settled-churn and
    # mid-flight late-arrival workloads must be bit-identical on the scan
    # AND pallas-interpret allocate paths with at least one speculative
    # cycle invalidated and replayed, and the sidecar serving ring must
    # hand back byte-identical payload streams at depth 1 and depth k
    env JAX_PLATFORMS=cpu python -m volcano_tpu.chaos --smoke --spec \
        > /tmp/_t1_spec.json || src=$?
fi
rrc=0
if [ "${TIER1_SKIP_RESTART:-0}" != "1" ]; then
    # restart smoke (volcano_tpu/chaos/restart): process_kill at all
    # three phases, each restored from the crash-consistent checkpoint
    # (runtime/checkpoint.py), decision-identical to the uninterrupted
    # run — plus the corrupt-checkpoint leg landing on the fallback rung
    env JAX_PLATFORMS=cpu python -m volcano_tpu.chaos --smoke --restart \
        > /tmp/_t1_restart.json || rrc=$?
fi
frc=0
if [ "${TIER1_SKIP_FAILOVER:-0}" != "1" ]; then
    # failover smoke (volcano_tpu/chaos/failover): leader_kill at all
    # three phases, each promoting the warm standby fed by checkpoint
    # streaming (runtime/replication.py) — the promotion must land warm
    # (cycles_to_steady == 0), decisions stay sha-identical to the
    # uninterrupted run costing at most one cycle, and the split-brain
    # leg's deposed-leader writes are fence-rejected, not applied
    env JAX_PLATFORMS=cpu python -m volcano_tpu.chaos --smoke --failover \
        > /tmp/_t1_failover.json || frc=$?
fi
mrc=0
if [ "${TIER1_SKIP_MESHLOSS:-0}" != "1" ]; then
    # elastic-mesh smoke (volcano_tpu/chaos/meshloss, ISSUE 20):
    # persistent device_loss faults on the 8-device CPU mesh must
    # quarantine + shrink the serving mesh 8->4->2, probation must
    # regrow it to 8, decisions stay sha-identical to the clean run on
    # scan AND pallas-interpret, and the device_flap leg proves the
    # probation backoff bounds re-mesh churn
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
        python -m volcano_tpu.chaos --smoke --meshloss \
        > /tmp/_t1_meshloss.json || mrc=$?
fi
flrc=0
if [ "${TIER1_SKIP_FLEET:-0}" != "1" ]; then
    # fleet smoke (volcano_tpu/fleet): N tenants served through one
    # batched vmapped dispatch per shape bucket — with churn, a mid-run
    # admission, and a mid-run eviction — must be decision-sha-identical
    # per tenant to N independent single-tenant runs, with the jit trace
    # counters proving one compiled program per (bucket, width)
    env JAX_PLATFORMS=cpu python -m volcano_tpu.fleet --smoke \
        > /tmp/_t1_fleet.json || flrc=$?
fi
qrc=0
if [ "${TIER1_SKIP_SCENARIO:-0}" != "1" ]; then
    # scheduling-quality smoke (volcano_tpu/scenarios): a short seeded
    # trace-replay run must produce a COMPLETE scorecard (non-null
    # makespan / DRF share error / utilization / wait quantiles) and its
    # CPU-oracle drift spot-checks must pass over real placements
    env JAX_PLATFORMS=cpu python -m volcano_tpu.scenarios --smoke \
        > /tmp/_t1_scenario.json || qrc=$?
fi
if [ $rc -ne 0 ]; then
    exit $rc
fi
if [ $grc -ne 0 ]; then
    exit $grc
fi
if [ $crc -ne 0 ]; then
    exit $crc
fi
if [ $src -ne 0 ]; then
    exit $src
fi
if [ $rrc -ne 0 ]; then
    exit $rrc
fi
if [ $frc -ne 0 ]; then
    exit $frc
fi
if [ $mrc -ne 0 ]; then
    exit $mrc
fi
if [ $flrc -ne 0 ]; then
    exit $flrc
fi
if [ $qrc -ne 0 ]; then
    exit $qrc
fi
exit $trc
