#!/usr/bin/env python
"""Validate a Chrome trace export + its summary (the tier-1 trace smoke).

Usage: python scripts/trace_check.py TRACE.json SUMMARY.json

Checks, exit 0 when all hold / 1 with a message when any fails:
- the trace file is valid JSON with a nonempty ``traceEvents`` list,
- every complete ("X") event carries the schema Perfetto needs
  (name/cat/ph/ts/dur/pid/tid, numeric timestamps),
- the summary (``python -m volcano_tpu.telemetry`` stdout) reports at
  least one in-flight device window with
  ``pipeline_overlap_fraction > 0`` — the pipelined loop's ingest work
  must actually overlap the device window, else the pipeline is lying.

Pure stdlib on purpose: the smoke proves the EXPORT is consumable
without the exporting process's imports.
"""

import json
import sys


def fail(msg):
    print("trace_check: FAIL: %s" % msg, file=sys.stderr)
    return 1


def main(argv):
    if len(argv) != 3:
        return fail("usage: trace_check.py TRACE.json SUMMARY.json")
    try:
        with open(argv[1]) as f:
            trace = json.load(f)
    except Exception as e:
        return fail("trace does not parse: %s: %s" % (type(e).__name__, e))
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return fail("traceEvents missing or empty")
    complete = [e for e in events if e.get("ph") == "X"]
    if not complete:
        return fail("no complete ('X') span events")
    for e in complete:
        for key in ("name", "cat", "ts", "dur", "pid", "tid"):
            if key not in e:
                return fail("X event missing %r: %r" % (key, e))
        if not isinstance(e["ts"], (int, float)) \
                or not isinstance(e["dur"], (int, float)):
            return fail("non-numeric ts/dur: %r" % e)
    if not any(e.get("cat") == "device" for e in complete):
        return fail("no device-window events in the trace")
    try:
        with open(argv[2]) as f:
            summary = json.load(f)
    except Exception as e:
        return fail("summary does not parse: %s: %s"
                    % (type(e).__name__, e))
    occ = summary.get("occupancy") or {}
    if not occ.get("windows"):
        return fail("occupancy reports zero device windows")
    frac = occ.get("pipeline_overlap_fraction")
    if summary.get("pipeline") and not (frac and frac > 0):
        return fail("pipelined run but pipeline_overlap_fraction=%r" % frac)
    print("trace_check: OK: %d events, %d windows, overlap %.3f"
          % (len(events), occ["windows"], frac or 0.0))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
