#!/usr/bin/env python
"""One-off: full-scale DRF (config 3) decision-equality record.

Runs the live CPU oracle at the full 8-queue/50k-task scale against the
kernel's dynamic dominant-resource ordering and stamps
drf_sha256/drf_cpu_ms into BENCH_BASELINE.json (VERDICT r4 #8); bench.py
then guards the record by fingerprint every run."""
import hashlib
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    from __graft_entry__ import _synthetic_cluster as _synth
    from volcano_tpu import native
    from volcano_tpu.api import QueueInfo
    from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                               AllocateExtras,
                                               derive_batching,
                                               make_allocate_cycle)
    from volcano_tpu.runtime.cpu_reference import allocate_cpu
    dci = _synth(n_nodes=1024, n_jobs=3125, tasks_per_job=16)
    for q in range(8):
        dci.add_queue(QueueInfo(f"q{q}", weight=1 + q % 4))
    for j, job in enumerate(dci.jobs.values()):
        job.queue = f"q{j % 8}"
    dsnap, _dm = native.pack_best_effort(dci)
    dextras = AllocateExtras.neutral(dsnap)
    # same conf derivation as bench.py's drf section: the dynamic-key
    # fused path on TPU, the XLA scan on CPU — decisions identical
    dcfg = derive_batching(
        AllocateConfig(binpack_weight=1.0, least_allocated_weight=0.0,
                       balanced_weight=0.0, taint_prefer_weight=0.0,
                       drf_job_order=True, enable_gpu=False),
        has_proportion=False)
    dfn = jax.jit(make_allocate_cycle(dcfg))
    res = dfn(dsnap, dextras)
    tn = np.asarray(res.task_node)
    t0 = time.time()
    res = dfn(dsnap, dextras)
    tn = np.asarray(res.task_node)
    tm = np.asarray(res.task_mode)
    tpu_ms = (time.time() - t0) * 1000
    print(f"kernel: {tpu_ms:.0f}ms placed={int((tm > 0).sum())}",
          flush=True)
    t0 = time.time()
    cpu = allocate_cpu(dsnap, dextras, dcfg)
    cpu_ms = (time.time() - t0) * 1000
    equal = bool(np.array_equal(tn, cpu["task_node"])
                 and np.array_equal(tm, cpu["task_mode"]))
    sha = hashlib.sha256(tn.tobytes() + tm.tobytes()).hexdigest()[:16]
    print(f"cpu oracle: {cpu_ms:.0f}ms equal={equal} sha={sha}", flush=True)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_BASELINE.json")
    rec = json.load(open(path))
    rec["drf_sha256"] = sha
    rec["drf_cpu_ms"] = round(cpu_ms, 1)
    rec["drf_equal_full_scale_verified"] = (
        time.strftime("%Y-%m-%d") if equal else None)
    json.dump(rec, open(path, "w"), indent=1)
    print("record updated")


if __name__ == "__main__":
    main()
