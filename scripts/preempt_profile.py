#!/usr/bin/env python
"""Dev profile: preempt kernel at BASELINE config-4 scale and adversarial
(~300 starving gangs) scale on the live chip. Not part of bench.py's
record; used to steer the round-5 preempt optimization (VERDICT r4 #2)."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _synthetic_cluster as _synth  # noqa: E402
from volcano_tpu.api import (JobInfo, PodGroupPhase, Resource,  # noqa: E402
                             TaskInfo, TaskStatus)
from volcano_tpu.ops.allocate_scan import AllocateConfig as _AC  # noqa: E402
from volcano_tpu.ops.preempt import PreemptConfig, make_preempt_cycle  # noqa


def scenario(n_nodes=10000, n_jobs=6000, n_gangs=64, gang_tasks=16,
             min_avail=8):
    pci = _synth(n_nodes=n_nodes, n_jobs=n_jobs, tasks_per_job=16)
    pnodes = list(pci.nodes)
    k = 0
    for job in pci.jobs.values():
        job.preemptable = True
        job.pod_group_phase = PodGroupPhase.RUNNING
        for t in job.tasks.values():
            nn = pnodes[k % len(pnodes)]
            k += 1
            t.status = TaskStatus.RUNNING
            t.node_name = nn
            pci.nodes[nn].add_task(t)
    for j in range(n_gangs):
        job = JobInfo(f"default/hp-{j:05d}", queue="default",
                      min_available=min_avail, priority=100,
                      creation_timestamp=float(j),
                      pod_group_phase=PodGroupPhase.INQUEUE)
        for t in range(gang_tasks):
            job.add_task(TaskInfo(
                uid=f"default/hp-{j:05d}-{t}", name=f"hp-{j:05d}-{t}",
                resreq=Resource.from_resource_list(
                    {"cpu": "1500m", "memory": "1Gi"})))
        pci.add_job(job)
    return pci


def run(tag, pci, reps=2):
    import jax
    from volcano_tpu import native as _nat
    from volcano_tpu.ops.allocate_scan import (MODE_PIPELINED,
                                               AllocateExtras)
    t0 = time.time()
    psnap, _pm = _nat.pack_best_effort(pci)
    pextras = AllocateExtras.neutral(psnap)
    pack_s = time.time() - t0
    pcfg = PreemptConfig(scoring=_AC(
        binpack_weight=1.0, least_allocated_weight=0.0,
        balanced_weight=0.0, taint_prefer_weight=0.0, enable_gpu=False))
    pT = psnap.tasks.status.shape[0]
    pveto = np.zeros(pT, bool)
    pskip = np.zeros(pT, bool)
    pfn = jax.jit(make_preempt_cycle(pcfg))
    t0 = time.time()
    pres = pfn(psnap, pextras, pveto, pskip)
    np.asarray(pres.evicted)
    compile_s = time.time() - t0
    times = []
    for _ in range(reps):
        t0 = time.time()
        pres = pfn(psnap, pextras, pveto, pskip)
        ev = np.asarray(pres.evicted)
        tm = np.asarray(pres.task_mode)
        times.append(time.time() - t0)
    print(f"{tag}: pack={pack_s:.1f}s compile={compile_s:.1f}s "
          f"cycle={min(times)*1000:.0f}ms victims={int(ev.sum())} "
          f"pipelined={int((tm == MODE_PIPELINED).sum())}", flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("config4", "both"):
        run("config4 (64 gangs x16, minav 8)", scenario())
    if which in ("adv", "both"):
        # adversarial: 312 starving gangs, 90 pending tasks each (~28k
        # pending), minAvailable 90 — most gangs cannot be served
        run("adversarial (312 gangs x90, minav 90)",
            scenario(n_gangs=312, gang_tasks=90, min_avail=90))
