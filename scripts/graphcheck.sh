#!/usr/bin/env bash
# Graphcheck — trace-time static analysis of the compiled scheduling
# cycle (volcano_tpu/analysis). Runs entirely on the CPU backend, so a
# dead TPU tunnel can never block the gate.
#
# Stable contract for bench/driver harnesses:
#   exit 0  clean            exit 1  findings          exit 2  internal error
#   the JSON report lands at $GRAPHCHECK_REPORT (default
#   /tmp/graphcheck_report.json) and its path is echoed on the last line.
#
# Extra CLI flags pass through (e.g. --fast, --families dtype,vmem).
set -o pipefail
cd "$(dirname "$0")/.."
REPORT="${GRAPHCHECK_REPORT:-/tmp/graphcheck_report.json}"
# the sharding family audits the compiled GSPMD module and the cost
# family's collective audit compiles the sharded entry at two node
# widths — both need a multi-device mesh, so give the CPU backend the
# same 8 virtual devices the test suite forces (tests/conftest.py)
# unless the caller already set XLA_FLAGS
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"
JAX_PLATFORMS=cpu python -m volcano_tpu.analysis --json "$REPORT" "$@"
rc=$?
echo "GRAPHCHECK_REPORT=$REPORT"
exit $rc
