#!/usr/bin/env python
"""One-off: full-scale affinity (config 5) decision-equality record.

Runs the live CPU oracle at the full 10k-node zone/rack scale against the
compiled cycle with inter-pod affinity enabled and stamps
affinity_sha256/affinity_cpu_ms into BENCH_BASELINE.json (VERDICT r5
item 3); bench.py then guards the record by fingerprint every run.
bench.py imports :func:`scenario` so the bench's measured cluster and the
recorded oracle cluster are the same object, keeping fingerprints
comparable."""
import hashlib
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def scenario(n_nodes=10000, n_jobs=2500, seed=0):
    """BASELINE.json config-5 shape: zone/rack topology, mixed required
    anti-affinity + preferred affinity terms over 8 apps."""
    from __graft_entry__ import _synthetic_cluster
    from volcano_tpu.api import PodAffinityTerm
    rng = np.random.RandomState(seed)
    ci = _synthetic_cluster(n_nodes=n_nodes, n_jobs=n_jobs, tasks_per_job=8)
    apps = [f"app{i}" for i in range(8)]
    for i, node in enumerate(ci.nodes.values()):
        node.labels["zone"] = f"z{i % 16}"
        node.labels["rack"] = f"r{i % max(1, n_nodes // 20)}"
    for j, job in enumerate(ci.jobs.values()):
        app = apps[j % len(apps)]
        for t in job.tasks.values():
            t.labels["app"] = app
            r = rng.rand()
            if r < 0.10:
                t.pod_anti_affinity = [PodAffinityTerm(
                    topology_key="rack", match_labels={"app": app})]
            elif r < 0.20:
                t.pod_affinity_preferred = [PodAffinityTerm(
                    topology_key="zone", match_labels={"app": app},
                    weight=10)]
    return ci


def build(ci):
    import dataclasses
    from volcano_tpu.arrays import pack
    from volcano_tpu.arrays.affinity import build_affinity
    from volcano_tpu.ops.allocate_scan import AllocateExtras
    snap, maps = pack(ci)
    N = snap.nodes.idle.shape[0]
    T = snap.tasks.resreq.shape[0]
    extras = dataclasses.replace(
        AllocateExtras.neutral(snap),
        affinity=build_affinity(ci, maps, N, T))
    return snap, extras


def main():
    import jax
    from volcano_tpu.ops.allocate_scan import (AllocateConfig,
                                               derive_batching,
                                               make_allocate_cycle)
    from volcano_tpu.runtime.cpu_reference import allocate_cpu
    n_nodes = int(os.environ.get("AFF_RECORD_NODES", 10000))
    n_jobs = int(os.environ.get("AFF_RECORD_JOBS", 2500))
    ci = scenario(n_nodes=n_nodes, n_jobs=n_jobs)
    snap, extras = build(ci)
    # static ordering keys + neutral deserved: derive_batching lands on
    # the K-batch path (K=8), same as bench's config-5 measurement
    acfg = derive_batching(
        AllocateConfig(binpack_weight=1.0, least_allocated_weight=0.0,
                       balanced_weight=0.0, taint_prefer_weight=0.0,
                       enable_pod_affinity=True, enable_gpu=False),
        has_proportion=False)
    afn = jax.jit(make_allocate_cycle(acfg))
    res = afn(snap, extras)
    tn = np.asarray(res.task_node)
    t0 = time.time()
    res = afn(snap, extras)
    tn = np.asarray(res.task_node)
    tm = np.asarray(res.task_mode)
    dev_ms = (time.time() - t0) * 1000
    print(f"kernel: {dev_ms:.0f}ms placed={int((tm > 0).sum())}", flush=True)
    t0 = time.time()
    cpu = allocate_cpu(snap, extras, acfg)
    cpu_ms = (time.time() - t0) * 1000
    equal = bool(np.array_equal(tn, cpu["task_node"])
                 and np.array_equal(tm, cpu["task_mode"]))
    sha = hashlib.sha256(tn.tobytes() + tm.tobytes()).hexdigest()[:16]
    print(f"cpu oracle: {cpu_ms:.0f}ms equal={equal} sha={sha}", flush=True)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_BASELINE.json")
    rec = json.load(open(path))
    rec["affinity_sha256"] = sha
    rec["affinity_cpu_ms"] = round(cpu_ms, 1)
    rec["affinity_config"] = {"nodes": n_nodes, "jobs": n_jobs,
                              "tasks_per_job": 8}
    rec["affinity_equal_full_scale_verified"] = (
        time.strftime("%Y-%m-%d") if equal else None)
    json.dump(rec, open(path, "w"), indent=1)
    print("record updated")


if __name__ == "__main__":
    main()
