#!/usr/bin/env python
"""One-off: time the sequential CPU oracle on the adversarial preempt
scenario (312 gangs x 90) and verify kernel decision equality at that
scale. Writes PREEMPT_ADV_RECORD.json for BASELINE.md."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.preempt_profile import scenario  # noqa: E402


def main():
    import jax
    from volcano_tpu import native
    from volcano_tpu.ops.allocate_scan import (MODE_PIPELINED,
                                               AllocateConfig,
                                               AllocateExtras)
    from volcano_tpu.ops.preempt import PreemptConfig, make_preempt_cycle
    from volcano_tpu.runtime.cpu_reference import preempt_cpu
    pci = scenario(n_gangs=312, gang_tasks=90, min_avail=90)
    snap, _ = native.pack_best_effort(pci)
    extras = AllocateExtras.neutral(snap)
    pcfg = PreemptConfig(scoring=AllocateConfig(
        binpack_weight=1.0, least_allocated_weight=0.0,
        balanced_weight=0.0, taint_prefer_weight=0.0, enable_gpu=False))
    T = snap.tasks.status.shape[0]
    veto = np.zeros(T, bool)
    skipm = np.zeros(T, bool)
    fn = jax.jit(make_preempt_cycle(pcfg))
    res = fn(snap, extras, veto, skipm)
    np.asarray(res.evicted)
    t0 = time.time()
    res = fn(snap, extras, veto, skipm)
    ev = np.asarray(res.evicted)
    tm = np.asarray(res.task_mode)
    tpu_ms = (time.time() - t0) * 1000
    print(f"tpu: {tpu_ms:.0f}ms victims={int(ev.sum())} "
          f"pipelined={int((tm == MODE_PIPELINED).sum())}", flush=True)
    t0 = time.time()
    cpu = preempt_cpu(snap, extras, veto, skipm, pcfg)
    cpu_ms = (time.time() - t0) * 1000
    equal = bool(
        np.array_equal(ev, cpu["evicted"])
        and np.array_equal(np.asarray(res.task_node), cpu["task_node"])
        and np.array_equal(tm, cpu["task_mode"]))
    print(f"cpu: {cpu_ms:.0f}ms equal={equal}", flush=True)
    import hashlib
    rec = dict(
        comment="Adversarial preempt record: 312 starving gangs x 90 tasks "
                "(28080 preemptors) over 10k nodes 75% full of preemptable "
                "Running tasks; 19418 victims. CPU path is the sequential "
                "numpy oracle (runtime/cpu_reference.preempt_cpu), the same "
                "loop the Go preempt action runs per task.",
        measured=time.strftime("%Y-%m-%d"),
        tpu_ms=round(tpu_ms, 1), cpu_ms=round(cpu_ms, 1),
        victims=int(ev.sum()),
        pipelined=int((tm == MODE_PIPELINED).sum()),
        decisions_equal=equal,
        preempt_adv_sha256=hashlib.sha256(
            np.asarray(res.task_node).tobytes() + tm.tobytes()
            + ev.tobytes()).hexdigest()[:16],
    )
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "PREEMPT_ADV_RECORD.json"),
            "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
