"""MXNet parameter-server training gang through the control plane.

The single-process analog of the reference's MXNet recipe
(example/integrations/mxnet/train/train-mnist-cpu.yaml): scheduler +
server + worker roles as one gang (minAvailable = all), svc plugin for the
DMLC_PS_ROOT_URI stable name, RestartJob on eviction/failure.

Run: python examples/integrations/mxnet.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from volcano_tpu.api.batch import Job, LifecyclePolicy, PodTemplate, TaskSpec
from volcano_tpu.api.types import BusAction, BusEvent
from volcano_tpu.runtime.system import VolcanoSystem


def mxnet_job(name="mxnet-job", workers=2, servers=2):
    res = {"cpu": "1", "memory": "1Gi"}
    return Job(
        name=name,
        min_available=1 + workers + servers,
        plugins={"svc": [], "env": []},
        policies=[
            LifecyclePolicy(action=BusAction.RESTART_JOB,
                            event=BusEvent.POD_EVICTED),
            LifecyclePolicy(action=BusAction.RESTART_JOB,
                            event=BusEvent.POD_FAILED),
        ],
        tasks=[
            TaskSpec(name="scheduler", replicas=1,
                     template=PodTemplate(resources=res)),
            TaskSpec(name="server", replicas=servers,
                     template=PodTemplate(resources=res)),
            TaskSpec(name="worker", replicas=workers,
                     template=PodTemplate(resources=res)),
        ])


def main():
    sys_ = VolcanoSystem()
    for i in range(3):
        sys_.add_node(f"node-{i}", cpu="8", memory="16Gi")
    sys_.submit_job(mxnet_job())
    for _ in range(3):
        sys_.tick()
    pods = sys_.pods_of("mxnet-job")
    print("pods:", [(p.name, p.phase, p.node_name) for p in pods])
    cm = sys_.api.get("configmaps", "default/mxnet-job-svc")
    print("scheduler host file:")
    print(cm.data["scheduler.host"])


if __name__ == "__main__":
    main()
