"""PaddlePaddle CTR training through the control plane.

The single-process analog of the reference's recipe
(example/integrations/paddlepaddle/ctr-paddlepaddle-on-volcano.yaml):
pserver + trainer roles as one gang with the svc plugin.

Run: python examples/integrations/paddle.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from volcano_tpu.api.batch import Job, PodTemplate, TaskSpec
from volcano_tpu.runtime.system import VolcanoSystem


def paddle_job(name="ctr-volcano", pservers=2, trainers=2):
    res = {"cpu": "1", "memory": "1Gi"}
    return Job(
        name=name,
        min_available=pservers + trainers,
        plugins={"svc": [], "env": []},
        tasks=[
            TaskSpec(name="pserver", replicas=pservers,
                     template=PodTemplate(resources=res)),
            TaskSpec(name="trainer", replicas=trainers,
                     template=PodTemplate(resources=res)),
        ])


def main():
    sys_ = VolcanoSystem()
    for i in range(2):
        sys_.add_node(f"node-{i}", cpu="8", memory="16Gi")
    sys_.submit_job(paddle_job())
    for _ in range(3):
        sys_.tick()
    pods = sys_.pods_of("ctr-volcano")
    print("pods:", [(p.name, p.phase, p.node_name) for p in pods])


if __name__ == "__main__":
    main()
