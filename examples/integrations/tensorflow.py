"""Distributed-TensorFlow gang job (ps + workers) through the control plane.

Analog of the reference's TF integration (test/e2e/jobseq/tensorflow.go):
the svc plugin publishes ps.host / worker.host files and VC_*_HOSTS env so
each member can assemble TF_CONFIG; gang scheduling guarantees ps and all
workers start together or not at all.

Run: python examples/integrations/tensorflow.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from volcano_tpu.api.batch import Job, LifecyclePolicy, PodTemplate, TaskSpec
from volcano_tpu.api.types import BusAction, BusEvent
from volcano_tpu.runtime.system import VolcanoSystem


def main():
    sys_ = VolcanoSystem()
    for i in range(3):
        sys_.add_node(f"node-{i}", cpu="8", memory="16Gi")

    job = Job(
        name="tf-dist-mnist",
        min_available=3,
        plugins={"svc": [], "env": []},
        tasks=[
            TaskSpec(name="ps", replicas=1,
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
            TaskSpec(name="worker", replicas=2,
                     policies=[LifecyclePolicy(
                         action=BusAction.COMPLETE_JOB,
                         event=BusEvent.TASK_COMPLETED)],
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
        ])
    sys_.submit_job(job)
    for _ in range(3):
        sys_.tick()

    pods = sys_.pods_of("tf-dist-mnist")
    print("pods:", [(p.name, p.phase, p.node_name) for p in pods])
    ps_pod = next(p for p in pods if "-ps-" in p.name)
    print("VC_WORKER_HOSTS:", ps_pod.env["VC_WORKER_HOSTS"])

    for i in range(2):
        sys_.finish_pod(f"default/tf-dist-mnist-worker-{i}", exit_code=0)
    for _ in range(4):
        sys_.tick()
    print("job phase:", sys_.job("tf-dist-mnist").status.state.phase)


if __name__ == "__main__":
    main()
