"""Argo-style workflow of Volcano jobs: step sequence + DAG fan-in.

The single-process analog of the reference's Argo recipes
(example/integrations/argo/10-job-step.yaml, 20-job-DAG.yaml): a workflow
engine submits Volcano Jobs as steps, waiting on each job's terminal phase
before releasing dependents. Here the 'engine' is a tiny driver over the
control plane's job phases — step A, then B and C in parallel, then D
after both.

Run: python examples/integrations/argo.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from volcano_tpu.api.batch import Job, PodTemplate, TaskSpec
from volcano_tpu.api.types import JobPhase
from volcano_tpu.runtime.system import VolcanoSystem


def step_job(name):
    return Job(name=name, min_available=1,
               tasks=[TaskSpec(name="main", replicas=1,
                               template=PodTemplate(
                                   resources={"cpu": "1",
                                              "memory": "512Mi"}))])


DAG = {"a": [], "b": ["a"], "c": ["a"], "d": ["b", "c"]}


def run_workflow(sys_, dag):
    done, submitted = set(), set()
    order = []
    for _ in range(32):
        for name, deps in dag.items():
            if name not in submitted and all(d in done for d in deps):
                sys_.submit_job(step_job(name))
                submitted.add(name)
        for _t in range(3):
            sys_.tick()
        for name in list(submitted - done):
            for p in sys_.pods_of(name):
                if p.node_name and p.phase not in ("Succeeded",):
                    sys_.finish_pod(p.uid, exit_code=0)
        for _t in range(3):
            sys_.tick()
        for name in list(submitted - done):
            if sys_.job(name).status.state.phase == JobPhase.COMPLETED:
                done.add(name)
                order.append(name)
        if len(done) == len(dag):
            break
    return order


def main():
    sys_ = VolcanoSystem()
    sys_.add_node("node-0", cpu="8", memory="16Gi")
    order = run_workflow(sys_, DAG)
    print("completion order:", order)
    assert order[0] == "a" and order[-1] == "d"


if __name__ == "__main__":
    main()
