"""MindSpore CPU training gang through the control plane.

The single-process analog of the reference's MindSpore example
(example/MindSpore-example/mindspore_cpu: an 8-replica gang with
minAvailable < replicas — an ELASTIC gang that starts at quorum).

Run: python examples/integrations/mindspore.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from volcano_tpu.api.batch import Job, PodTemplate, TaskSpec
from volcano_tpu.runtime.system import VolcanoSystem


def mindspore_job(name="mindspore-cpu", replicas=8, min_available=5):
    return Job(
        name=name,
        min_available=min_available,
        plugins={"svc": []},
        tasks=[TaskSpec(name="pod", replicas=replicas,
                        template=PodTemplate(
                            resources={"cpu": "1", "memory": "512Mi"}))])


def main():
    sys_ = VolcanoSystem()
    # capacity for the quorum but not all replicas: the elastic gang starts
    for i in range(3):
        sys_.add_node(f"node-{i}", cpu="2", memory="8Gi")
    sys_.submit_job(mindspore_job())
    for _ in range(3):
        sys_.tick()
    pods = sys_.pods_of("mindspore-cpu")
    running = [p for p in pods if p.node_name]
    print(f"placed {len(running)}/8 replicas (minAvailable=5)")


if __name__ == "__main__":
    main()
