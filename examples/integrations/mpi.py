"""MPI gang job through the assembled control plane.

The single-process analog of the reference's MPI integration
(example/integrations + test/e2e/jobseq/mpi.go): a master + workers gang with
the ssh/svc/env job plugins, so the master can `mpiexec --hostfile
/etc/volcano/mpiworker.host` over password-less ssh once every member runs.

Run: python examples/integrations/mpi.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from volcano_tpu.api.batch import Job, LifecyclePolicy, PodTemplate, TaskSpec
from volcano_tpu.api.types import BusAction, BusEvent
from volcano_tpu.runtime.system import VolcanoSystem


def main():
    sys_ = VolcanoSystem()
    for i in range(3):
        sys_.add_node(f"node-{i}", cpu="8", memory="16Gi")

    job = Job(
        name="mpi",
        min_available=3,
        plugins={"ssh": [], "svc": [], "env": []},
        policies=[LifecyclePolicy(action=BusAction.COMPLETE_JOB,
                                  event=BusEvent.TASK_COMPLETED)],
        tasks=[
            TaskSpec(name="mpimaster", replicas=1,
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
            TaskSpec(name="mpiworker", replicas=2,
                     template=PodTemplate(resources={"cpu": "1",
                                                     "memory": "1Gi"})),
        ])
    sys_.submit_job(job)
    for _ in range(3):
        sys_.tick()

    pods = sys_.pods_of("mpi")
    print("pods:", [(p.name, p.phase, p.node_name) for p in pods])
    cm = sys_.api.get("configmaps", "default/mpi-svc")
    print("mpiworker.host:")
    print(cm.data["mpiworker.host"])

    # the master's mpiexec finishes -> the whole job completes
    sys_.finish_pod("default/mpi-mpimaster-0", exit_code=0)
    for _ in range(4):
        sys_.tick()
    print("job phase:", sys_.job("mpi").status.state.phase)


if __name__ == "__main__":
    main()
