#!/usr/bin/env python
"""Installer: bootstrap a volcano_tpu control plane (the helm chart analog,
reference installer/helm/chart/volcano).

The reference installs CRDs, the scheduler ConfigMap, webhook
registrations, and the three deployments into a k8s cluster; here the
"cluster" is the in-process VolcanoSystem, so installing means: validate
the CRD manifests ship intact, load a scheduler conf preset, assemble the
system (scheduler + controllers + webhooks), and optionally persist it as
a --state file the vcctl/v* CLIs operate on.

Usage:
    python deploy/install.py --conf conf/volcano-scheduler.conf --state /tmp/vc.state
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

EXPECTED_CRDS = {
    "batch.volcano.sh_jobs.yaml": "jobs.batch.volcano.sh",
    "scheduling.volcano.sh_podgroups.yaml": "podgroups.scheduling.volcano.sh",
    "scheduling.volcano.sh_queues.yaml": "queues.scheduling.volcano.sh",
    "bus.volcano.sh_commands.yaml": "commands.bus.volcano.sh",
    "nodeinfo.volcano.sh_numatopologies.yaml":
        "numatopologies.nodeinfo.volcano.sh",
}


def check_crds() -> list:
    """Validate the shipped CRD manifests (install CRDs step)."""
    import yaml
    names = []
    for fname, crd_name in EXPECTED_CRDS.items():
        path = os.path.join(HERE, "crd", fname)
        with open(path) as f:
            doc = yaml.safe_load(f)
        assert doc["kind"] == "CustomResourceDefinition", fname
        assert doc["metadata"]["name"] == crd_name, fname
        versions = doc["spec"]["versions"]
        assert any(v.get("storage") for v in versions), fname
        names.append(crd_name)
    return names


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="install volcano_tpu")
    ap.add_argument("--conf", default=os.path.join(ROOT, "conf",
                                                   "volcano-scheduler.conf"),
                    help="scheduler policy preset (conf/*.conf)")
    ap.add_argument("--state", default=None,
                    help="write the assembled system here for the CLIs")
    args = ap.parse_args(argv)

    crds = check_crds()
    for c in crds:
        print(f"customresourcedefinition {c} installed")

    from volcano_tpu.framework.conf import parse_conf
    from volcano_tpu.runtime.system import VolcanoSystem
    from volcano_tpu.version import version_string
    with open(args.conf) as f:
        conf = parse_conf(f.read())
    system = VolcanoSystem(conf=conf)
    print(f"scheduler conf {os.path.basename(args.conf)} loaded "
          f"({len(conf.actions)} actions, "
          f"{sum(len(t.plugins) for t in conf.tiers)} plugins)")
    if args.state:
        with open(args.state, "wb") as f:
            pickle.dump(system, f)
        print(f"system state written to {args.state}")
    print(version_string())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
